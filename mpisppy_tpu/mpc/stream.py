###############################################################################
# MPC streams on the wheel server (ISSUE 19 tentpole, piece 4;
# docs/mpc.md, docs/serving.md streaming lifecycle).
#
# An MPC session (SubmitRequest.mpc_steps > 0) is one LONG-LIVED
# latency-class session: the serve engine routes it here instead of the
# one-wheel solve, and the stream emits one `step` protocol line per
# solved window over the existing JSON-lines connection.  The pieces:
#
#   per-step accounting   every completed window calls
#                         Session.note_step, which re-arms the per-step
#                         deadline (the streaming reaper's
#                         consecutive-miss budget, serve/server.py) and
#                         charges the step through WFQ
#                         (admission.charge_step) — a stream pays per
#                         window, so it can never starve throughput
#                         tenants;
#   preemption survival   after every window the stream checkpoint
#                         (next step index + the SHIFTED warm plane —
#                         the base key is derived from {model args,
#                         step}, so it rides in the argv) is written
#                         atomically to the session spool.  A preempted
#                         stream returns the engine's standard
#                         ('preempted', ...) verdict, re-enters the
#                         queue front, and the resumed worker re-solves
#                         the SAME window from the SAME plane —
#                         bit-identical resampling (horizon.py), so the
#                         resumed stream reproduces the fault-free
#                         stream's per-step bounds exactly;
#   telemetry             mpc-step / mpc-degraded events on the
#                         session's scoped bus (-> session-<sid>.jsonl
#                         -> telemetry watch's per-stream step-latency
#                         row) + mpc_* metrics.
###############################################################################
from __future__ import annotations

import os
import time

import numpy as np

from mpisppy_tpu import telemetry as tel
from mpisppy_tpu.resilience.faults import PreemptionError
from mpisppy_tpu.telemetry import metrics as _metrics


def _load_checkpoint(path: str | None):
    """(next_step, plane) from the stream checkpoint, or (0, None)."""
    if not path or not os.path.exists(path):
        return 0, None
    try:
        with np.load(path) as z:
            return int(z["next_step"]), {
                "W": np.asarray(z["W"]),
                "xbar_nodes": np.asarray(z["xbar_nodes"]),
                "x": np.asarray(z["x"]),
            }
    except Exception:
        # an unreadable/torn checkpoint restarts the stream cold — the
        # window data is still bit-identical (pure in {argv, step})
        return 0, None


def _save_checkpoint(path: str | None, next_step: int, plane: dict):
    """Atomic replace, the hub checkpoint convention — a preemption
    mid-write leaves the previous step's file intact."""
    if not path:
        return
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, next_step=np.int64(next_step), W=plane["W"],
                 xbar_nodes=plane["xbar_nodes"], x=plane["x"])
    os.replace(tmp, path)


def run_stream(session, fault_plan=None) -> tuple:
    """Run one MPC session to completion (or preemption).  Same
    verdict surface as WheelEngine.run: ('done', payload) or
    ('preempted', payload); raises on a failed build (the server types
    it for the client)."""
    from mpisppy_tpu.mpc.driver import RollingDriver
    from mpisppy_tpu.mpc.horizon import horizon_for

    if fault_plan is not None:
        fault_plan.serve_before_solve(session.tenant, session.ordinal)
    spec = session.spec
    horizon = horizon_for(spec)
    hub_options = {
        "run_id": session.run_id,
        "telemetry_bus": session.bus,
        "preempt_event": session.preempt_event,
    }
    if fault_plan is not None:
        hub_options["fault_plan"] = fault_plan
    driver = RollingDriver(horizon, hub_options=hub_options)

    start, plane = 0, None
    if session.restore:
        start, plane = _load_checkpoint(session.checkpoint_path)
        if plane is None:
            # no (readable) spool: restart from the session's own
            # cursor, cold — deterministic data, but the warm plane is
            # gone, so only the spool path preserves per-step bounds
            start = int(session.mpc_step)
        session.mpc_step = start
        _metrics.REGISTRY.inc("mpc_stream_resumes_total")
    else:
        _metrics.REGISTRY.inc("mpc_streams_total")
    session.reset_step_anchor()

    latencies, degraded_steps, warm_steps, cold_fallbacks = [], 0, 0, 0
    last = None
    # per-window spans parent under the session's current segment
    # (one child span per MPC step — ISSUE 20)
    seg = getattr(session, "segment", None) \
        or getattr(session, "trace", None)
    for k in range(start, int(spec.mpc_steps)):
        step_span = seg.child() if seg is not None else None
        if step_span is not None:
            # the window's whole event stream (hub iterations, spans,
            # dispatch joins) rides the step span; the segment scope is
            # restored below — or by end_segment on a preemption
            session.bus.set_trace(step_span)
        session.bus.emit(
            tel.SPAN_START, run=session.run_id, cyl="mpc",
            trace=step_span, name="mpc-step", session=session.sid,
            step=k)
        t0 = time.perf_counter()
        try:
            res = driver.run_step(k, warm_plane=plane)
        except PreemptionError as e:
            # the stream checkpoint from step k-1 is the resume point:
            # the re-admitted worker re-solves window k from the same
            # shifted plane, bit-identically
            return "preempted", {"step": k, "detail": str(e)}
        latency = time.perf_counter() - t0
        plane = driver.next_plane(res)
        _save_checkpoint(session.checkpoint_path, k + 1, plane)
        session.note_step(k, rel_gap=res.rel_gap)
        latencies.append(latency)
        warm_steps += 1 if res.warm else 0
        cold_fallbacks += 1 if res.cold_fallback else 0
        degraded_steps += 1 if res.degraded else 0
        last = res
        session.bus.emit(
            tel.MPC_STEP, run=session.run_id, cyl="mpc",
            trace=step_span,
            session=session.sid, tenant=session.tenant, step=k,
            outer=res.outer, inner=res.inner, rel_gap=res.rel_gap,
            iterations=res.iterations, warm=res.warm,
            cold_fallback=res.cold_fallback, degraded=res.degraded,
            latency_s=latency)
        if res.degraded:
            session.bus.emit(
                tel.MPC_DEGRADED, run=session.run_id, cyl="mpc",
                trace=step_span,
                session=session.sid, step=k, rel_gap=res.rel_gap,
                gap_target=horizon.gap_target)
            _metrics.REGISTRY.inc("mpc_degraded_steps_total")
        _metrics.REGISTRY.inc("mpc_steps_total")
        if res.warm:
            _metrics.REGISTRY.inc("mpc_warm_steps_total")
        if res.cold_fallback:
            _metrics.REGISTRY.inc("mpc_cold_fallbacks_total")
        _metrics.REGISTRY.set_gauge("mpc_step_latency_s", latency)
        _metrics.REGISTRY.observe("mpc_step_latency_hist_s", latency)
        session.send({
            "event": "step", "session": session.sid, "step": k,
            "outer": res.outer, "inner": res.inner,
            "rel_gap": res.rel_gap, "warm": res.warm,
            "degraded": res.degraded, "latency_s": round(latency, 4),
            "x_root": [round(float(v), 6) for v in res.x_root]})
    if seg is not None:
        session.bus.set_trace(seg)   # leave the last step's span
    if session.checkpoint_path:
        try:
            os.remove(session.checkpoint_path)
        except OSError:
            pass
    lat = np.asarray(latencies) if latencies else np.zeros(1)
    return "done", {
        "steps": int(spec.mpc_steps),
        "warm_steps": warm_steps,
        "cold_fallbacks": cold_fallbacks,
        "degraded_steps": degraded_steps,
        "rel_gap": None if last is None else float(last.rel_gap),
        "outer": None if last is None else float(last.outer),
        "inner": None if last is None else float(last.inner),
        "step_latency_p50_s": round(float(np.percentile(lat, 50)), 4),
        "step_latency_p99_s": round(float(np.percentile(lat, 99)), 4),
        "preemptions": session.preemptions,
    }
