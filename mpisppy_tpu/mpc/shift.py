###############################################################################
# Warm-start shift kernel (ISSUE 19 tentpole, piece 2; docs/mpc.md).
#
# Between two MPC steps the decision window advances by `stride`: slot
# (g, t) of the new window corresponds to slot (g, t + stride) of the
# old one, so the previous step's converged PH plane — duals W (S, N),
# node averages x̄ (nodes, N), incumbent nonants x (S, N) — is ROLLED
# forward along the nonant axis and the tail entries that have no
# rolled source are SPLICED fresh.  Everything is a single gather:
#
#     new[..., i] = old[..., src_idx[i]]          (then W *= 1 - fresh)
#
# The splice policy per plane:
#   W      zeroed on fresh tail slots — a dual carries step-k pricing
#          information that does not exist yet for a slot entering the
#          window, and a zero column keeps the p-weighted node-mean-zero
#          PH invariant (every ROLLED column keeps it automatically:
#          the same gather applies to all scenarios of a column).
#   x̄, x   persistence-filled (src_idx points fresh tails at the last
#          in-window source slot) — the standard receding-horizon
#          primal initializer.
#
# TRACE PURITY / COMPILE STABILITY: shift_state is a module-level jit
# whose every input is a traced array (src_idx and fresh_mask included —
# they are DATA, not static), so step 2..K of a stream re-dispatch the
# step-1 executable: zero warm recompiles, pinned by the compile-count
# regression test (tests/test_mpc.py) and audited as the `mpc_shift_state`
# graftir manifest entry (tools/graftlint/ir/manifest.py).
###############################################################################
from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class ShiftPlan:
    """One horizon's nonant-axis shift, as data.

    src_idx:    (N,) int32 — new slot i reads old slot src_idx[i].
    fresh_mask: (N,) float32 — 1.0 where slot i entered the window this
                step (no rolled source; W is zeroed there), else 0.0.
    """

    src_idx: np.ndarray
    fresh_mask: np.ndarray

    def __post_init__(self):
        src = np.asarray(self.src_idx, np.int32)
        fresh = np.asarray(self.fresh_mask, np.float32)
        if src.shape != fresh.shape or src.ndim != 1:
            raise ValueError(
                f"src_idx {src.shape} and fresh_mask {fresh.shape} must "
                f"be the same (N,) vector")
        if src.size and (src.min() < 0 or src.max() >= src.size):
            raise ValueError("src_idx entries must index the same window")
        object.__setattr__(self, "src_idx", src)
        object.__setattr__(self, "fresh_mask", fresh)

    @property
    def num_nonants(self) -> int:
        return int(self.src_idx.size)


def uc_plan(n_gens: int, n_hours: int, stride: int = 1) -> ShiftPlan:
    """uc nonants are u_{g,t} in g-major layout (slot = g*T + t): hour
    t of the new window was hour t + stride of the old one; the last
    `stride` hours of each generator are fresh (persistence-filled from
    the generator's final in-window hour)."""
    G, T = int(n_gens), int(n_hours)
    stride = int(stride)
    if not (0 < stride <= T):
        raise ValueError(f"stride {stride} outside (0, {T}]")
    src = np.empty(G * T, np.int32)
    fresh = np.zeros(G * T, np.float32)
    for g in range(G):
        for t in range(T):
            rolled = t + stride
            if rolled < T:
                src[g * T + t] = g * T + rolled
            else:
                src[g * T + t] = g * T + (T - 1)
                fresh[g * T + t] = 1.0
    return ShiftPlan(src_idx=src, fresh_mask=fresh)


def ccopf_plan(n_gens: int) -> ShiftPlan:
    """ccopf nonants are generator setpoints at stages 1 and 2
    (stage-major, N = 2*ng): advancing one decision epoch makes the old
    stage-2 plan the new stage-1 plan, and the new stage-2 slots are
    fresh (persistence-filled from old stage 2)."""
    ng = int(n_gens)
    src = np.concatenate([np.arange(ng, 2 * ng),
                          np.arange(ng, 2 * ng)]).astype(np.int32)
    fresh = np.concatenate([np.zeros(ng), np.ones(ng)]).astype(np.float32)
    return ShiftPlan(src_idx=src, fresh_mask=fresh)


def _shift_state_impl(W, xbar_nodes, x_non, src_idx, fresh_mask):
    import jax.numpy as jnp
    keep = (1.0 - fresh_mask).astype(W.dtype)
    return (jnp.take(W, src_idx, axis=-1) * keep,
            jnp.take(xbar_nodes, src_idx, axis=-1),
            jnp.take(x_non, src_idx, axis=-1))


_shift_state_jit = None


def shift_state(W, xbar_nodes, x_non, src_idx, fresh_mask):
    """THE shift kernel: (W, x̄_nodes, x) rolled by src_idx with fresh-
    tail W zeroing.  One process-wide jit, every argument traced, so
    every step of every stream with the same shapes shares one
    executable (lazily created so importing mpc costs no jax import)."""
    global _shift_state_jit
    if _shift_state_jit is None:
        import jax
        _shift_state_jit = jax.jit(_shift_state_impl)
    return _shift_state_jit(W, xbar_nodes, x_non, src_idx, fresh_mask)


def shift_warm_plane(plane: dict, plan: ShiftPlan) -> dict:
    """Host bridge: the end-of-step warm plane (numpy dict with W,
    xbar_nodes, x) shifted into next step's seed through the jitted
    kernel.  Deterministic, so a preempted stream that re-shifts the
    checkpointed plane reproduces the uninterrupted stream exactly."""
    import jax.numpy as jnp
    W = np.asarray(plane["W"])
    dt = W.dtype
    w, xb, x = shift_state(
        jnp.asarray(W), jnp.asarray(plane["xbar_nodes"], dt),
        jnp.asarray(plane["x"], dt),
        jnp.asarray(plan.src_idx), jnp.asarray(plan.fresh_mask))
    return {"W": np.asarray(w), "xbar_nodes": np.asarray(xb),
            "x": np.asarray(x)}
