###############################################################################
# RollingDriver (ISSUE 19 tentpole, piece 3; docs/mpc.md).
#
# One receding-horizon step = one fused cylinder wheel built through the
# SAME generic_cylinders recipe surface the CLI and serve engine use,
# with the previous step's shifted W/x̄ plane seeded into the hub at its
# first sync (cylinders/hub.py warm_plane option — the WXBarReader
# timing, without the file round-trip).  The driver's whole job is the
# per-step policy around that wheel:
#
#   warm attempt     solve window k from the shifted plane to the
#                    per-step gap target within the step's iteration
#                    budget (--max-iterations: the watchdog-style
#                    budget — a stalled step EXHAUSTS it, never hangs);
#   cold fallback    if the warm attempt misses the target (gap stall)
#                    or poisons the bounds (infeasible shifted iterate
#                    → non-finite gap), re-solve the SAME window cold —
#                    the plane is a hint, never a correctness input;
#   StepDegraded     if the cold solve ALSO misses, the step is typed
#                    degraded (recorded on the StepResult; strict=True
#                    raises) and the stream continues — one hard window
#                    must not kill a control loop.
#
# Determinism contract: window k's data is a pure function of
# {base_seed, k} (horizon.py), and the warm plane is a pure function of
# window k-1's converged state (shift.py), so a preempted stream that
# re-runs step k from the checkpointed plane reproduces the
# uninterrupted stream's per-step bounds exactly (stream.py leans on
# this; tests/test_mpc.py pins it).
###############################################################################
from __future__ import annotations

import dataclasses
import importlib
import math
import time

import numpy as np


class StepDegraded(RuntimeError):
    """Window `step` missed the per-step gap target warm AND cold —
    the stream continues on the best iterate, typed for telemetry
    (mpc-degraded) and for strict callers."""

    def __init__(self, step: int, rel_gap: float, target: float):
        super().__init__(
            f"mpc step {step}: rel_gap {rel_gap:.3e} missed target "
            f"{target:.3e} after cold fallback")
        self.step = step
        self.rel_gap = rel_gap
        self.target = target


@dataclasses.dataclass
class StepResult:
    """One solved window."""

    step: int
    outer: float
    inner: float
    rel_gap: float
    iterations: int
    warm: bool                 # solved from a shifted plane
    cold_fallback: bool        # warm attempt discarded, re-solved cold
    degraded: bool             # missed the gap target even cold
    solve_seconds: float
    x_root: np.ndarray         # stage-1 nonants of the incumbent
    plane: dict                # end-of-step {W, xbar_nodes, x} (UNshifted)


def _step_ok(rel_gap: float, target: float) -> bool:
    return math.isfinite(rel_gap) and rel_gap <= target + 1e-12


class RollingDriver:
    """The receding-horizon loop over one HorizonSpec."""

    def __init__(self, horizon, hub_options: dict | None = None):
        self.horizon = horizon
        #: extra hub options every window gets (stream.py threads the
        #: session bus / run id / preempt_event through here)
        self.hub_options = dict(hub_options or {})
        argv = horizon.base_argv
        self._module_name = argv[argv.index("--module-name") + 1]
        self._module = importlib.import_module(self._module_name)

    # -- one window -----------------------------------------------------
    def _spin(self, step: int, warm_plane: dict | None):
        from mpisppy_tpu import generic_cylinders as gc
        from mpisppy_tpu.spin_the_wheel import WheelSpinner
        cfg = gc._parse_args(self._module, self.horizon.step_argv(step))
        hub, spokes, _names, _specs, _batch = gc.build_wheel(
            cfg, self._module)
        hub = dict(hub)
        hub["hub_kwargs"] = dict(hub.get("hub_kwargs", {}))
        hub_opts = dict(hub["hub_kwargs"].get("options", {}))
        hub_opts.update(self.hub_options)
        if warm_plane is not None:
            hub_opts["warm_plane"] = warm_plane
        hub["hub_kwargs"]["options"] = hub_opts
        wheel = WheelSpinner(hub, spokes)
        wheel.build()
        t0 = time.perf_counter()
        # PreemptionError propagates: a drained window restarts whole
        # from the stream checkpoint (plane + step), which is exact
        wheel.spin()
        dt = time.perf_counter() - t0
        _abs_gap, rel_gap = wheel.spcomm.compute_gaps()
        opt = wheel.opt
        st = opt.state
        plane = {
            "W": np.asarray(st.W),
            "xbar_nodes": np.asarray(st.xbar_nodes),
            "x": np.asarray(opt.batch.nonants(st.solver.x)),
        }
        nodes = wheel.spcomm.best_nonants()
        root = np.asarray(nodes[0])[
            np.asarray(opt.batch.tree.slot_stage) == 1]
        return {
            "outer": float(wheel.BestOuterBound),
            "inner": float(wheel.BestInnerBound),
            "rel_gap": float(rel_gap),
            "iterations": int(wheel.spcomm._iter),
            "solve_seconds": dt,
            "x_root": root,
            "plane": plane,
        }

    def run_step(self, step: int, warm_plane: dict | None = None,
                 strict: bool = False) -> StepResult:
        """Solve window `step`, warm from `warm_plane` when given, cold
        fallback + degraded typing per the module header."""
        warm = warm_plane is not None
        out = self._spin(step, warm_plane)
        cold_fallback = False
        if warm and not _step_ok(out["rel_gap"],
                                 self.horizon.gap_target):
            cold_fallback = True
            out = self._spin(step, None)
        degraded = not _step_ok(out["rel_gap"], self.horizon.gap_target)
        if degraded and strict:
            raise StepDegraded(step, out["rel_gap"],
                               self.horizon.gap_target)
        return StepResult(
            step=step, outer=out["outer"], inner=out["inner"],
            rel_gap=out["rel_gap"], iterations=out["iterations"],
            warm=warm and not cold_fallback,
            cold_fallback=cold_fallback, degraded=degraded,
            solve_seconds=out["solve_seconds"],
            x_root=out["x_root"], plane=out["plane"])

    # -- the stream -----------------------------------------------------
    def next_plane(self, result: StepResult) -> dict:
        """The warm plane for result.step + 1 (the shift kernel over
        the end-of-step plane)."""
        from mpisppy_tpu.mpc.shift import shift_warm_plane
        return shift_warm_plane(result.plane, self.horizon.plan)

    def stream(self, num_steps: int, start: int = 0,
               warm_plane: dict | None = None):
        """Yield StepResults for windows start .. start+num_steps-1,
        rolling the plane between them.  `warm_plane` resumes a
        checkpointed stream (stream.py); step `start` solves cold when
        it is None."""
        plane = warm_plane
        for k in range(start, start + num_steps):
            res = self.run_step(k, warm_plane=plane)
            plane = self.next_plane(res)
            yield res
