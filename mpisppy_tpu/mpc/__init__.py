###############################################################################
# Rolling-horizon MPC streams (ISSUE 19 tentpole; docs/mpc.md).
#
# Receding-horizon control re-solves a nearly identical stochastic
# program every step with shifted data — the regime PAPERS.md's
# accelerated-proximal-gradient MPC line (arXiv:2109.04405) targets with
# warm-started first-order iterations, and the batched-solve surface
# MPAX (arXiv:2412.09734) treats as a product.  This package composes
# the pieces that already landed — W/x̄ warm-start IO, shape-bucketed
# compile caching, scengen's fold_in(base, step) re-keying, and the
# latency/throughput serve classes — into that product:
#
#   horizon.py  declarative HorizonSpec (window, stride, per-step data
#               shift) + model hooks for uc and ccopf --soc
#   shift.py    trace-pure warm-start shift kernel rolling W/x̄/x
#               forward by the stride (zero warm recompiles)
#   driver.py   RollingDriver: the shifted wheel to a per-step gap
#               target, cold-start fallback, typed StepDegraded
#   stream.py   the serve-layer integration: one long-lived latency
#               session streaming one solution line per step
###############################################################################
from mpisppy_tpu.mpc.driver import RollingDriver, StepDegraded, StepResult
from mpisppy_tpu.mpc.horizon import (
    HorizonSpec,
    ccopf_horizon,
    horizon_for,
    uc_horizon,
)
from mpisppy_tpu.mpc.shift import ShiftPlan, shift_state, shift_warm_plane

__all__ = [
    "HorizonSpec", "RollingDriver", "ShiftPlan", "StepDegraded",
    "StepResult", "ccopf_horizon", "horizon_for", "shift_state",
    "shift_warm_plane", "uc_horizon",
]
