###############################################################################
# The wheel fleet: N serve-layer replicas — each a full WheelServer
# with its own engine, device stream, structure interner, trace
# subdirectory and socket — behind ONE router that owns global
# admission (WFQ, quotas, SLA), structure-affine placement, replica
# health (heartbeats + status probes), and live session migration
# (emergency checkpoint on the source, restore-from-spool on the
# destination, the Session settle latch keeping terminal delivery
# exactly-once).  ISSUE 16; docs/serving.md fleet section.
###############################################################################
from mpisppy_tpu.fleet.health import DEAD, SUSPECT, UP, HealthBoard
from mpisppy_tpu.fleet.migration import Migrator
from mpisppy_tpu.fleet.placement import choose, routing_key
from mpisppy_tpu.fleet.replica import Replica
from mpisppy_tpu.fleet.router import FleetOptions, FleetRouter

__all__ = [
    "DEAD", "SUSPECT", "UP", "HealthBoard", "Migrator", "choose",
    "routing_key", "Replica", "FleetOptions", "FleetRouter",
]
