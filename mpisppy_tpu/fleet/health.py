###############################################################################
# Fleet health plane (ISSUE 16 tentpole; docs/serving.md fleet
# section).
#
# Replica liveness rides heartbeats into the router: each replica's
# beat thread refreshes its last-beat clock every heartbeat_s, and the
# router's monitor ages those clocks through this board:
#
#   UP ──(beat stale > miss_budget beats)──> SUSPECT
#   SUSPECT ──(status probe over the replica socket answers)──> stays
#             SUSPECT (a slow-heartbeat replica is degraded, not dead)
#   SUSPECT ──(probe fails too)──> DEAD  (fenced: sticky — a replica
#             that reappears after a partition is NOT readmitted, so a
#             split brain can never double-assign; the settle latch
#             is the second line of defense)
#   SUSPECT ──(beats resume)──> UP  (recovered)
#
# Every transition emits one `replica-state` event on the router bus.
###############################################################################
from __future__ import annotations

import threading

from mpisppy_tpu import telemetry as tel

UP = "UP"
SUSPECT = "SUSPECT"
DEAD = "DEAD"


class HealthBoard:
    """The router's view of replica liveness (see module header).
    observe() is called by the monitor loop with the two signals it
    has — beat freshness and, when stale, the socket probe verdict —
    and returns the new state when a transition happened (None
    otherwise).  DEAD is sticky (fencing)."""

    def __init__(self, bus=None, run_id: str = ""):
        self.bus = bus
        self.run_id = run_id
        self._lock = threading.Lock()
        self._state: dict = {}        # guarded-by: _lock

    def state(self, rid: str) -> str:
        with self._lock:
            return self._state.get(rid, UP)

    def _move(self, rid: str, new: str):   # holds-lock: _lock
        old = self._state.get(rid, UP)
        if old == new or old == DEAD:
            return None
        self._state[rid] = new
        return old

    def observe(self, rid: str, fresh: bool,
                probe_ok: bool | None = None,
                reason: str = "") -> str | None:
        """One monitor reading.  fresh = the replica's beat clock is
        within the miss budget; probe_ok = the status-probe verdict
        (only consulted when stale).  Returns the entered state on a
        transition."""
        if fresh:
            new = UP
        elif probe_ok:
            new = SUSPECT
        else:
            new = DEAD
        with self._lock:
            old = self._move(rid, new)
        if old is None:
            return None
        if self.bus is not None:
            self.bus.emit(tel.REPLICA_STATE, run=self.run_id,
                          cyl="fleet", replica=rid, state=new,
                          prev=old, reason=reason)
        return new

    def force(self, rid: str, new: str, reason: str = "") -> str | None:
        """Out-of-band transition (a replica's own kill seam, a drain
        decision) — same stickiness and event emission as observe."""
        with self._lock:
            old = self._move(rid, new)
        if old is None:
            return None
        if self.bus is not None:
            self.bus.emit(tel.REPLICA_STATE, run=self.run_id,
                          cyl="fleet", replica=rid, state=new,
                          prev=old, reason=reason)
        return new

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._state)
