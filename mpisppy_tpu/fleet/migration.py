###############################################################################
# Live session migration (ISSUE 16 tentpole; docs/serving.md fleet
# section).
#
# The single-node preemption path (emergency checkpoint at the next
# hub sync -> requeue FRONT with restore=True -> load_checkpoint)
# generalized to a routed operation between replicas sharing one
# checkpoint spool:
#
#   source replica                router                 destination
#   ──────────────                ──────                 ───────────
#   preempt_event set ─┐
#   hub raises at sync │
#   emergency ckpt ────┤
#   worker hands off ──┼─> hand_off(): release quota,
#                      │   detach source trace, emit
#                      │   session-migrated, requeue
#                      │   FRONT with restore=True ────> pop_placed()
#                      │                                 submit_session
#                      │                                 load_checkpoint
#                      │                                 (CRC-validated,
#                      │                                 rotation
#                      │                                 fallback)
#
# Exactly-one-terminal is carried by the Session.settle latch — the
# SAME Session object travels, so even a partitioned source replica
# racing its migrated copy cannot deliver a second outcome.  A session
# that cannot complete the move (no live replica, a worker wedged past
# the drain grace) settles `failed` typed and counts into
# fleet_migrations_lost_total — the counter the regression gate pins
# to zero.
###############################################################################
from __future__ import annotations

import threading

from mpisppy_tpu import telemetry as tel
from mpisppy_tpu.telemetry import metrics as _metrics


class Migrator:
    """The router's migration bookkeeping: the hand-off entry points
    (running and queued flavors) and the dead-replica rescue sweep."""

    def __init__(self, router):
        self.router = router
        # Lock discipline (tools/graftlint lock-discipline): counters
        # are bumped from replica worker threads and drain threads.
        self._lock = threading.Lock()
        self.started = 0              # guarded-by: _lock
        self.completed = 0            # guarded-by: _lock (hand-offs
                                      # that re-entered the queue)
        self.lost = 0                 # guarded-by: _lock

    def counters(self) -> dict:
        with self._lock:
            return {"started": self.started,
                    "completed": self.completed, "lost": self.lost}

    # -- the running-session hand-off (worker thread of the source) -------
    def hand_off(self, session, payload: dict, replica) -> bool:
        """Take a draining replica's preempted session: the emergency
        checkpoint is on disk (shared spool), the worker already moved
        the session to DEGRADED with restore=True.  Returns True —
        ownership passes to the router."""
        router = self.router
        with self._lock:
            self.started += 1
        session.preempt_event.clear()
        session.migrations += 1
        # the hand-off opens a dedicated MIGRATION child span under the
        # request root (ISSUE 20): the source segment already detached
        # (server._handle_preemption), the destination's begin_segment
        # opens a sibling — so the wall from this span's start to the
        # next segment's start IS the migration gap spans.py puts on
        # the critical path.  The rows land in the SOURCE trace file
        # (the sink is still attached), the router stream, and the
        # client.
        mig = session.trace.child()
        for bus in (session.bus, router.bus):
            bus.emit(tel.SPAN_START, run=session.run_id, cyl="fleet",
                     trace=mig, name="migration", session=session.sid,
                     from_replica=replica.id)
            bus.emit(tel.SESSION_MIGRATED, run=session.run_id,
                     cyl="fleet", session=session.sid, trace=mig,
                     tenant=session.tenant,
                     from_replica=replica.id,
                     iter=payload.get("iter"),
                     migrations=session.migrations)
        session.detach_trace()
        _metrics.REGISTRY.inc("fleet_sessions_migrated_total")
        router._unassign(session)
        if router.stopping:
            self.mark_lost(session, reason="draining",
                           detail="preempted while the fleet drained; "
                                  "checkpoint retained")
            return True
        router.admission.requeue_front(session)
        with self._lock:
            self.completed += 1
        router.kick()
        return True

    # -- the queued-session hand-off (drain thread of the source) ---------
    def requeue_queued(self, session, replica) -> None:
        """A session that was still QUEUED on the draining replica:
        no checkpoint involved, it simply re-enters the global queue
        (front — it already waited once)."""
        router = self.router
        router.bus.emit(tel.SESSION_MIGRATED, run=session.run_id,
                        cyl="fleet", session=session.sid,
                        trace=session.trace,
                        tenant=session.tenant, from_replica=replica.id,
                        queued=True, migrations=session.migrations)
        router._unassign(session)
        if router.stopping:
            self.mark_lost(session, reason="draining",
                           detail="queued on a drained replica while "
                                  "the fleet stopped")
            return
        router.admission.requeue_front(session)
        router.kick()

    # -- failure accounting ------------------------------------------------
    def mark_lost(self, session, reason: str, detail: str = "") -> None:
        """A migration that could not complete: typed terminal failure
        + the any-increase-gated loss counter (only when THIS call
        delivered the outcome — a session the deadline reaper already
        settled is its failure, not a migration loss)."""
        if session.settle("failed", reason=reason, detail=detail):
            _metrics.REGISTRY.inc("serve_failures_total")
            _metrics.REGISTRY.inc("fleet_migrations_lost_total")
            with self._lock:
                self.lost += 1

    # -- the dead-replica rescue sweep (drain thread) ----------------------
    def rescue(self, replica, grace_s: float) -> None:
        """After a replica's drain grace: any session still assigned
        there and non-terminal failed to hand itself off (a wedged
        worker on a dead box) — it settles typed NOW rather than
        hanging a client forever."""
        import time
        router = self.router
        deadline = time.perf_counter() + float(grace_s)
        while time.perf_counter() < deadline:
            if not router.assigned_to(replica.id):
                return
            time.sleep(0.02)
        for session in router.assigned_to(replica.id):
            if not session.is_terminal():
                self.mark_lost(
                    session, reason="replica-dead",
                    detail=f"replica {replica.id} died and the "
                           f"session did not hand off within "
                           f"{grace_s}s")
            router._unassign(session)
