###############################################################################
# Replica placement (ISSUE 16 tentpole; docs/serving.md fleet section).
#
# The scheduling unit is STRUCTURE, not tenant: two sessions solving
# the same model at the same scale intern to the same canonical arrays
# (serve/multiplex.StructureInterner), and the dispatch scheduler
# coalesces their oracle calls only when they share one interner pool
# — i.e. when they land on the SAME replica.  So the router derives a
# content-addressed routing key from the session spec (the projection
# of the interner digest that is knowable BEFORE the batch is built)
# and places:
#
#   1. AFFINITY   — a live replica with free slots that already holds
#                   the session's routing key (its interner already
#                   has the canonical structure; the megabatch
#                   coalescing is free there);
#   2. LEAST-LOADED — otherwise the live replica with the most free
#                   slots (ties broken by replica id for determinism);
#   3. DECLINE    — no live replica has a free slot: the session stays
#                   queued in FleetAdmission, uncharged.
###############################################################################
from __future__ import annotations

import hashlib

from mpisppy_tpu.serve.protocol import SubmitRequest


def routing_key(spec: SubmitRequest) -> str:
    """The content-addressed placement key of a session spec: sessions
    with equal keys build identical shared structure (model module,
    scenario count, structure-affecting args), so equal keys coalesce
    on one replica.  A hash collision or a miss only costs
    coalescence, never correctness — exactly the interner contract."""
    ident = (spec.model, spec.num_scens, tuple(spec.args))
    return hashlib.sha1(repr(ident).encode()).hexdigest()[:16]


def choose(session, candidates: list) -> tuple:
    """Pick the replica for `session` from live candidates (each a
    fleet.replica.Replica with free slots).  Returns (replica, policy)
    with policy 'affinity' | 'least-loaded', or (None, 'none') when no
    candidate is given."""
    if not candidates:
        return None, "none"
    key = session.structure_key
    with_key = [r for r in candidates if key and r.holds(key)]
    if with_key:
        pool, policy = with_key, "affinity"
    else:
        pool, policy = candidates, "least-loaded"
    best = max(pool, key=lambda r: (r.free_slots(), r.id))
    return best, policy
