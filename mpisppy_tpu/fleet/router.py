###############################################################################
# The fleet router (ISSUE 16 tentpole; docs/serving.md fleet section).
#
# One admission tier over N serve replicas: clients speak the SAME
# JSON-lines protocol to the router socket (submit / ping / stats /
# status), but admission policy — WFQ weights, per-tenant quotas, SLA
# classes, bounded queues with typed rejection — lives HERE, in one
# FleetAdmission above the replicas.  The scheduler loop fuses the WFQ
# pop with placement (serve/admission.FleetAdmission.pop_placed +
# fleet/placement.choose): structure-affine first, least-loaded
# otherwise, and a fleet without free slots leaves the queue charged
# to nobody.
#
# Thread anatomy (every shared field lock-annotated; tools/graftlint
# lock-discipline):
#
#   acceptor ── one reader per client (same shape as serve/server.py)
#   scheduler ── pop_placed -> WheelServer.submit_session on the chosen
#     replica; doubles as the deadline reaper for sessions still queued
#     at the router (assigned sessions are reaped by their replica)
#   monitor ── ages the replicas' heartbeat clocks through the
#     HealthBoard; a stale replica is status-probed over its own
#     socket (alive-but-slow = SUSPECT, unreachable = DEAD -> fence,
#     drain, migrate)
#   drain threads ── one per dead replica: queued sessions requeue,
#     running sessions emergency-checkpoint and hand off (live
#     migration, fleet/migration.py), stragglers settle typed
#
# The exactly-one-terminal contract is unchanged from PR 11: the same
# Session object travels router -> replica -> router -> replica, and
# its settle latch admits one delivery no matter how many paths race.
###############################################################################
from __future__ import annotations

import dataclasses
import os
import socket
import threading
import time

from mpisppy_tpu import telemetry as tel
from mpisppy_tpu.fleet import health, migration, placement
from mpisppy_tpu.fleet import replica as replica_mod
from mpisppy_tpu.serve import admission as adm
from mpisppy_tpu.serve import protocol
from mpisppy_tpu.serve import server as srv_mod
from mpisppy_tpu.serve import session as sess_mod
from mpisppy_tpu.telemetry import metrics as _metrics


@dataclasses.dataclass(frozen=True)
class FleetOptions:
    """Router + replica fleet knobs."""

    unix_path: str | None = None     # router socket (replica sockets
                                     # derive as <unix_path>.<rid>)
    host: str = "127.0.0.1"          # TCP fallback (replicas get
    port: int = 0                    # ephemeral ports)
    n_replicas: int = 3
    max_running_per_replica: int = 2
    max_queued: int = 64             # GLOBAL queue cap (router-owned)
    max_queued_per_tenant: int = 32
    tenant_quota: int = 2            # GLOBAL per-tenant in-flight cap
    tenant_weights: dict | None = None
    latency_burst: int = 4
    trace_dir: str | None = None     # replica traces land in <rid>/
                                     # subdirs; router events in
                                     # fleet.jsonl
    spool_dir: str | None = None     # SHARED checkpoint spool — the
                                     # migration transport
    multiplex: bool = True
    default_deadline_s: float | None = None
    heartbeat_s: float = 0.2
    miss_budget: int = 3             # stale beats before probing/death
    drain_grace_s: float = 5.0       # emergency-checkpoint window
    probe_timeout_s: float = 1.0
    engine_factory: object | None = None  # callable(rid) -> engine;
                                     # None = one WheelEngine with its
                                     # OWN StructureInterner per
                                     # replica (its own device stream's
                                     # structure pool)
    fault_plan: object | None = None
    bus: object | None = None


class FleetRouter:
    """See the module header."""

    def __init__(self, options: FleetOptions = FleetOptions()):
        self.options = options
        self.bus = options.bus or tel.EventBus()
        self.run_id = tel.new_run_id()
        for d in (options.trace_dir, options.spool_dir):
            if d:
                os.makedirs(d, exist_ok=True)
        if options.trace_dir:
            self.bus.subscribe(tel.JsonlSink(
                os.path.join(options.trace_dir, "fleet.jsonl")))
        self.admission = adm.FleetAdmission(
            max_queued=options.max_queued,
            max_queued_per_tenant=options.max_queued_per_tenant,
            default_quota=options.tenant_quota,
            weights=options.tenant_weights,
            latency_burst=options.latency_burst)
        self.migrator = migration.Migrator(self)
        self.board = health.HealthBoard(bus=self.bus,
                                        run_id=self.run_id)
        self._sock: socket.socket | None = None
        self.address = None
        # Lock discipline (tools/graftlint lock-discipline): registry,
        # assignment map and lifecycle flags are shared by the
        # acceptor, readers, scheduler, monitor, replica workers (via
        # on_terminal / hand-off) and drain threads.
        self._lock = threading.Lock()
        self._sessions: dict = {}         # guarded-by: _lock (live +
                                          # bounded terminal tail)
        self._assigned: dict = {}         # guarded-by: _lock
                                          # (sid -> replica id)
        self._state_totals: dict = {}     # guarded-by: _lock
        self._submitted = 0               # guarded-by: _lock
        self._stopping = False            # guarded-by: _lock
        self._downed: set = set()         # guarded-by: _lock
        self._threads: list = []          # guarded-by: _lock
        self._wake = threading.Condition(self._lock)
        self.keep_terminal = 256
        self.replicas: list = []
        for i in range(int(options.n_replicas)):
            rid = f"r{i}"
            self.replicas.append(replica_mod.Replica(
                rid, self._replica_options(rid),
                heartbeat_s=options.heartbeat_s,
                fault_plan=options.fault_plan,
                on_down=self._replica_down,
                router_handoff=self.migrator.hand_off))

    def _replica_options(self, rid: str) -> srv_mod.ServeOptions:
        o = self.options
        r_trace = os.path.join(o.trace_dir, rid) if o.trace_dir \
            else None
        engine = o.engine_factory(rid) if o.engine_factory else None
        if engine is None:
            from mpisppy_tpu.serve import multiplex as mux
            from mpisppy_tpu.serve.engine import WheelEngine
            engine = WheelEngine(
                multiplexed=o.multiplex,
                interner=mux.StructureInterner())
        cap = max(2, int(o.max_running_per_replica))
        return srv_mod.ServeOptions(
            unix_path=f"{o.unix_path}.{rid}" if o.unix_path else None,
            host=o.host, port=0,
            max_running=o.max_running_per_replica,
            # the LOCAL queue is just the assignment buffer: caps wide
            # enough to never bind (global backpressure is the
            # router's), quota = slots so local WFQ never withholds
            max_queued=4 * cap, max_queued_per_tenant=4 * cap,
            tenant_quota=cap,
            latency_burst=o.latency_burst,
            trace_dir=r_trace, spool_dir=o.spool_dir,
            multiplex=o.multiplex,
            default_deadline_s=o.default_deadline_s,
            engine=engine, fault_plan=o.fault_plan,
            bus=self.bus, replica_id=rid)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "FleetRouter":
        for r in self.replicas:
            r.start()
        o = self.options
        if o.unix_path:
            try:
                os.unlink(o.unix_path)
            except OSError:
                pass
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.bind(o.unix_path)
            self.address = o.unix_path
        else:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((o.host, o.port))
            self.address = s.getsockname()
        s.listen(64)
        s.settimeout(0.25)
        self._sock = s
        for name, target in (("fleet-accept", self._accept_loop),
                             ("fleet-sched", self._schedule_loop),
                             ("fleet-monitor", self._monitor_loop)):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._note_thread(t)
        _metrics.REGISTRY.set_gauge("fleet_replicas_up",
                                    len(self.replicas))
        tel.console.log(
            f"fleet: router on {self.address} "
            f"({len(self.replicas)} replicas x "
            f"{o.max_running_per_replica} slots)")
        return self

    def stop(self, timeout: float = 10.0) -> None:
        with self._lock:
            self._stopping = True
            self._wake.notify_all()
        for s in self.admission.drain():
            self._reject(s, "draining")
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            with self._lock:
                if not self._assigned:
                    break
            time.sleep(0.05)
        for r in self.replicas:
            r.close(timeout=1.0)
        # leftovers (a wedged worker on a replica we just closed):
        # typed terminal outcome, never a hang
        with self._lock:
            leftovers = [s for s in self._sessions.values()
                         if not s.is_terminal()]
        for s in leftovers:
            if s.settle("failed", reason="draining",
                        detail="fleet stopped before the session "
                               "finished"):
                _metrics.REGISTRY.inc("serve_failures_total")
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self.options.unix_path:
            try:
                os.unlink(self.options.unix_path)
            except OSError:
                pass
        if self.options.bus is None:
            self.bus.close()

    @property
    def stopping(self) -> bool:
        with self._lock:
            return self._stopping

    def kick(self) -> None:
        with self._lock:
            self._wake.notify_all()

    # -- client plumbing (same shape as serve/server.py) ------------------
    def _accept_loop(self):
        while True:
            with self._lock:
                if self._stopping:
                    return
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._client_loop,
                                 args=(conn,), daemon=True,
                                 name="fleet-client")
            t.start()
            self._note_thread(t)

    def _client_loop(self, conn: socket.socket):
        wlock = threading.Lock()
        my_sessions: list = []

        def outbox(msg: dict):
            data = protocol.encode(msg)
            with wlock:
                conn.sendall(data)

        try:
            rfile = conn.makefile("rb")
            for msg in protocol.iter_lines(rfile):
                if "_malformed" in msg:
                    srv_mod.WheelServer._safe_send(outbox, {
                        "ok": False, "error": "malformed-json",
                        "detail": msg["_malformed"][:200]})
                    continue
                op = msg.get("op")
                if op == "ping":
                    srv_mod.WheelServer._safe_send(
                        outbox, {"ok": True, "op": "ping"})
                elif op == "stats":
                    srv_mod.WheelServer._safe_send(
                        outbox, {"ok": True, "op": "stats",
                                 "stats": self.stats()})
                elif op == "status":
                    srv_mod.WheelServer._safe_send(
                        outbox, {"ok": True, "op": "status",
                                 "status": self.status()})
                elif op == "submit":
                    try:
                        self._handle_submit(msg, outbox, my_sessions)
                    except Exception as e:  # noqa: BLE001 — typed ack
                        srv_mod.WheelServer._safe_send(outbox, {
                            "ok": False, "error": "internal",
                            "detail": f"{type(e).__name__}: "
                                      f"{e}"[:300]})
                else:
                    srv_mod.WheelServer._safe_send(outbox, {
                        "ok": False, "error": "unknown-op", "op": op})
        except (OSError, ValueError):
            pass
        finally:
            for s in my_sessions:
                s.detach()
            try:
                conn.close()
            except OSError:
                pass

    def _handle_submit(self, msg: dict, outbox, my_sessions: list):
        try:
            spec = protocol.SubmitRequest.from_dict(msg)
        except protocol.ProtocolError as e:
            srv_mod.WheelServer._safe_send(
                outbox, {"ok": False, "error": "bad-request",
                         "detail": str(e)})
            return
        if spec.deadline_s is None \
                and self.options.default_deadline_s is not None:
            spec = dataclasses.replace(
                spec, deadline_s=self.options.default_deadline_s)
        # the session's trace attaches per replica at assignment; the
        # checkpoint path is router-assigned so it stays STABLE across
        # replicas (the shared spool is the migration transport)
        session = sess_mod.Session(spec, outbox=outbox,
                                   server_bus=self.bus)
        session.structure_key = placement.routing_key(spec)
        if self.options.spool_dir:
            session.checkpoint_path = os.path.join(
                self.options.spool_dir, f"ckpt-{session.sid}.npz")
        try:
            self.admission.submit(session)
        except adm.AdmissionRejected as e:
            self.bus.emit(tel.ADMISSION_REJECTED, run=session.run_id,
                          cyl="serve", tenant=spec.tenant,
                          trace=session.trace,
                          reason=e.reason, detail=e.detail)
            _metrics.REGISTRY.inc("serve_admission_rejects_total")
            session.settle("rejected", reason=e.reason,
                           detail=e.detail)
            srv_mod.WheelServer._safe_send(
                outbox, {"ok": False, "session": session.sid,
                         "error": "rejected", "reason": e.reason})
            return
        with self._lock:
            self._sessions[session.sid] = session
            self._submitted += 1
            self._wake.notify_all()
        my_sessions.append(session)
        _metrics.REGISTRY.inc("serve_sessions_total")
        srv_mod.WheelServer._safe_send(
            outbox, {"ok": True, "session": session.sid,
                     "tenant": spec.tenant})

    def _reject(self, session, reason: str, detail: str = ""):
        if session.is_terminal():
            return
        if session.state == sess_mod.DEGRADED:
            session.settle("failed", reason=reason,
                           detail=detail or "migrating while the "
                           "fleet drained; checkpoint retained")
            return
        self.bus.emit(tel.ADMISSION_REJECTED, run=session.run_id,
                      cyl="serve", tenant=session.tenant,
                      trace=session.trace,
                      reason=reason, detail=detail)
        _metrics.REGISTRY.inc("serve_admission_rejects_total")
        session.settle("rejected", reason=reason, detail=detail)

    # -- scheduling: WFQ pop fused with placement -------------------------
    def _schedule_loop(self):
        while True:
            with self._lock:
                if self._stopping:
                    return
            popped, rep = self.admission.pop_placed(self._place)
            if popped is not None:
                self._assign(popped, rep)
                continue
            self._reap_queued_deadlines()
            with self._lock:
                if self._stopping:
                    return
                self._wake.wait(timeout=0.05)

    def _place(self, session):
        candidates = [r for r in self.replicas
                      if r.alive() and r.free_slots() > 0]
        rep, policy = placement.choose(session, candidates)
        if rep is not None:
            session.placement_policy = policy
        return rep

    def _assign(self, session, rep) -> None:
        session.on_terminal = self._session_terminal
        with self._lock:
            self._assigned[session.sid] = rep.id
        try:
            rep.server.submit_session(session)
        except adm.AdmissionRejected:
            # the replica began draining between placement and submit:
            # undo the charge and let the scheduler re-place it
            self._unassign(session)
            if not self.stopping:
                self.admission.requeue_front(session)
            return
        rep.note_key(session.structure_key)
        policy = getattr(session, "placement_policy", "least-loaded")
        _metrics.REGISTRY.inc(
            "fleet_placement_affinity_total" if policy == "affinity"
            else "fleet_placement_spill_total")
        # stamped with the session's ROOT span: placement is a hop of
        # the request itself, not of any one run segment (ISSUE 20)
        self.bus.emit(tel.FLEET_PLACEMENT, run=session.run_id,
                      cyl="fleet", session=session.sid,
                      trace=session.trace,
                      tenant=session.tenant, replica=rep.id,
                      policy=policy, key=session.structure_key,
                      migrations=session.migrations)

    def _unassign(self, session) -> None:
        """Drop the session's assignment and give its global quota
        charge back — exactly once per charge (the assignment entry is
        the latch)."""
        with self._lock:
            had = self._assigned.pop(session.sid, None) is not None
        if had:
            self.admission.release(session)

    def _session_terminal(self, session) -> None:
        self._unassign(session)
        self.kick()
        self._prune_sessions()

    def assigned_to(self, rid: str) -> list:
        with self._lock:
            return [self._sessions[sid]
                    for sid, r in self._assigned.items()
                    if r == rid and sid in self._sessions]

    def _reap_queued_deadlines(self) -> None:
        """Deadline enforcement for sessions still queued at the
        ROUTER (assigned sessions are reaped by their replica's own
        reaper)."""
        now = time.perf_counter()
        with self._lock:
            cands = [s for s in self._sessions.values()
                     if s.deadline is not None and now >= s.deadline
                     and not s.is_terminal()
                     and s.sid not in self._assigned]
        for s in cands:
            if s.settle("failed", reason="deadline",
                        detail=f"session deadline "
                               f"{s.spec.deadline_s}s expired queued "
                               f"at the router"):
                _metrics.REGISTRY.inc("serve_failures_total")

    # -- bounded registries -----------------------------------------------
    def _note_thread(self, t) -> None:
        with self._lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _prune_sessions(self) -> None:
        with self._lock:
            terminal = [s for s in self._sessions.values()
                        if s.is_terminal()
                        and s.sid not in self._assigned]
            excess = len(terminal) - max(0, int(self.keep_terminal))
            for s in terminal[:max(0, excess)]:
                self._state_totals[s.state] = \
                    self._state_totals.get(s.state, 0) + 1
                del self._sessions[s.sid]

    # -- the health plane -------------------------------------------------
    def _monitor_loop(self):
        o = self.options
        while True:
            with self._lock:
                if self._stopping:
                    return
            time.sleep(o.heartbeat_s)
            for rep in self.replicas:
                if self.board.state(rep.id) == health.DEAD:
                    continue
                fresh = rep.beat_age() <= o.heartbeat_s * o.miss_budget
                probe_ok = None if fresh else self._probe(rep)
                new = self.board.observe(
                    rep.id, fresh, probe_ok,
                    reason="" if fresh else "missed-beats")
                if new == health.DEAD:
                    self._replica_down(rep, "missed-beats")

    def _probe(self, rep) -> bool:
        """Deep health check: the status op over the replica's own
        socket.  A partition suppresses it (the seam models the router
        side of the cut); a slow-but-alive replica answers."""
        plan = self.options.fault_plan
        if plan is not None \
                and plan.replica_partitioned(rep.id, rep.beats()):
            return False
        try:
            addr = rep.server.address
            if isinstance(addr, str):
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.connect(addr)
            else:
                s = socket.create_connection(tuple(addr))
            try:
                s.settimeout(self.options.probe_timeout_s)
                s.sendall(protocol.encode({"op": "status"}))
                line = s.makefile("rb").readline()
            finally:
                s.close()
            if not line:
                return False
            import json
            return bool(json.loads(line).get("ok"))
        except (OSError, ValueError):
            return False

    def _replica_down(self, rep, reason: str) -> None:
        """Fence a dead replica and migrate its sessions — idempotent
        (the kill seam and the monitor can both get here)."""
        with self._lock:
            if rep.id in self._downed:
                return
            self._downed.add(rep.id)
        self.board.force(rep.id, health.DEAD, reason=reason)
        _metrics.REGISTRY.inc("fleet_replica_deaths_total")
        t = threading.Thread(target=self._drain_replica,
                             args=(rep, reason), daemon=True,
                             name=f"fleet-drain-{rep.id}")
        t.start()
        self._note_thread(t)

    def _drain_replica(self, rep, reason: str) -> None:
        grace = self.options.drain_grace_s
        rep.drain(self.migrator.requeue_queued, grace_s=grace)
        self.migrator.rescue(rep, grace_s=grace)
        _metrics.REGISTRY.set_gauge(
            "fleet_replicas_up",
            sum(1 for r in self.replicas if r.alive()))
        self.bus.emit(tel.REPLICA_STATE, run=self.run_id, cyl="fleet",
                      replica=rep.id, state="DRAINED", prev="DEAD",
                      reason=reason)
        self.kick()

    # -- stats / status ---------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            counts = dict(self._state_totals)
            for s in self._sessions.values():
                counts[s.state] = counts.get(s.state, 0) + 1
            out = {
                "submitted": self._submitted,
                "assigned": len(self._assigned),
                "states": counts,
            }
        out["admission"] = self.admission.stats()
        out["migration"] = self.migrator.counters()
        out["health"] = self.board.snapshot()
        out["replicas"] = {r.id: r.server.stats()
                          for r in self.replicas}
        return out

    def status(self) -> dict:
        """The fleet-level health summary (mirrors the per-replica
        status op one level up)."""
        with self._lock:
            assigned = len(self._assigned)
        return {
            "replicas": {
                r.id: {"state": self.board.state(r.id),
                       "alive": r.alive(),
                       "free_slots": r.free_slots(),
                       "beats": r.beats()}
                for r in self.replicas},
            "queued": self.admission.stats()["queued"],
            "assigned": assigned,
            "migration": self.migrator.counters(),
        }
