###############################################################################
# One fleet replica (ISSUE 16 tentpole; docs/serving.md fleet
# section).
#
# A Replica wraps a full PR-11 WheelServer — its own socket (the
# status/ping health ops ride it), its own engine with its own
# StructureInterner (one device stream's worth of structure pool), its
# own trace subdirectory (trace_dir/<rid>/) — plus the fleet plumbing:
#
#   * a HEARTBEAT thread refreshing the router-visible beat clock every
#     heartbeat_s, through the ReplicaFault seams (kill stops the loop,
#     partition suppresses the refresh, slow_heartbeat delays it);
#   * a HAND-OFF seam: while the replica drains, a preempted session is
#     handed back to the router (WheelServer._preemption_handoff)
#     instead of the local queue — the live-migration exit door;
#   * DRAIN: queued sessions hand back immediately, running sessions
#     get their preempt_event set so the hub raises at its next sync
#     prologue (emergency checkpoint = the SIGTERM grace window a real
#     preemption grants), and the wrapper waits out the grace period.
#
# The replica's LOCAL FairQueue is deliberately non-binding (quota =
# max_running): global WFQ/quota/SLA policy lives in the router's
# FleetAdmission; locally the queue is just the assignment buffer.
###############################################################################
from __future__ import annotations

import threading
import time

from mpisppy_tpu.serve import server as srv_mod


class _ReplicaServer(srv_mod.WheelServer):
    """WheelServer whose preemption path can hand a session back to
    the fleet router (see WheelServer._preemption_handoff)."""

    def __init__(self, options, handoff=None):
        super().__init__(options)
        self._handoff = handoff

    def _preemption_handoff(self, session, payload: dict) -> bool:
        if self._handoff is None:
            return False
        return self._handoff(session, payload)


class Replica:
    """One replica of the serve fleet (see module header)."""

    def __init__(self, rid: str, options: srv_mod.ServeOptions,
                 heartbeat_s: float = 0.2, fault_plan=None,
                 on_down=None, router_handoff=None,
                 max_keys: int = 256):
        self.id = rid
        self.heartbeat_s = float(heartbeat_s)
        self.fault_plan = fault_plan
        self.max_running = options.max_running
        self._on_down = on_down              # callable(replica, reason)
        self._router_handoff = router_handoff  # callable(session,
                                               # payload, replica)->bool
        self.server = _ReplicaServer(options, handoff=self._maybe_handoff)
        # Lock discipline (tools/graftlint lock-discipline): the beat
        # clock and liveness flags are shared by the beat thread, the
        # router's monitor/scheduler, and the drain thread.
        self._lock = threading.Lock()
        self._beats = 0                   # guarded-by: _lock
        self.last_beat = time.perf_counter()  # guarded-by: _lock
        self._dead = False                # guarded-by: _lock
        self._draining = False            # guarded-by: _lock
        self._closed = False              # guarded-by: _lock
        self._keys: dict = {}             # guarded-by: _lock (bounded
                                          # FIFO of routing keys held)
        self._max_keys = int(max_keys)
        self._beat_thread = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Replica":
        self.server.start()
        t = threading.Thread(target=self._beat_loop, daemon=True,
                             name=f"fleet-beat-{self.id}")
        t.start()
        self._beat_thread = t
        return self

    def close(self, timeout: float = 2.0) -> None:
        with self._lock:
            self._closed = True
        self.server.stop(timeout=timeout)

    # -- heartbeats (through the ReplicaFault seams) ----------------------
    def _beat_loop(self) -> None:
        plan = self.fault_plan
        while True:
            with self._lock:
                if self._dead or self._closed:
                    return
                beat = self._beats
                self._beats += 1
            if plan is not None and plan.replica_kill(self.id, beat):
                # the abrupt death: heartbeats stop, the router fences
                # and drains us (the SIGTERM grace window)
                if self._on_down is not None:
                    self._on_down(self, "killed")
                return
            if not (plan is not None
                    and plan.replica_partitioned(self.id, beat)):
                with self._lock:
                    self.last_beat = time.perf_counter()
            delay = plan.replica_beat_delay(self.id) if plan else 0.0
            time.sleep(self.heartbeat_s + delay)

    def beats(self) -> int:
        with self._lock:
            return self._beats

    def beat_age(self) -> float:
        with self._lock:
            return time.perf_counter() - self.last_beat

    # -- liveness / load (the router's placement reads) -------------------
    def alive(self) -> bool:
        with self._lock:
            return not (self._dead or self._draining or self._closed)

    @property
    def dead(self) -> bool:
        with self._lock:
            return self._dead

    def free_slots(self) -> int:
        if not self.alive():
            return 0
        running, queued = self.server.load()
        return max(0, self.max_running - running - queued)

    # -- placement-affinity key set ---------------------------------------
    def holds(self, key: str) -> bool:
        with self._lock:
            return key in self._keys

    def note_key(self, key: str) -> None:
        if not key:
            return
        with self._lock:
            self._keys.pop(key, None)
            self._keys[key] = True
            while len(self._keys) > self._max_keys:
                self._keys.pop(next(iter(self._keys)))

    # -- migration hand-off ------------------------------------------------
    def _maybe_handoff(self, session, payload: dict) -> bool:
        """Preemption-path seam: hand the session to the router when
        this replica is going away; a plain (chaos-injected)
        preemption on a healthy replica keeps the local
        requeue-with-restore path."""
        with self._lock:
            migrating = self._draining or self._dead
        if not migrating or self._router_handoff is None:
            return False
        return self._router_handoff(session, payload, self)

    # -- drain (the migration source half) --------------------------------
    def drain(self, requeue_queued, grace_s: float = 5.0) -> None:
        """Take this replica out of service: locally queued sessions
        hand back through `requeue_queued(session, replica)`, running
        sessions get their preempt_event set (the hub checkpoints and
        the worker hands off at the next sync), and we wait out the
        grace window before closing the server."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
            self._dead = True
        for s in self.server.queue.drain():
            if not s.is_terminal():
                requeue_queued(s, self)
        # slot holders = exactly the sessions a worker thread owns
        # (covers the pop->RUNNING window a state scan would race)
        with self.server._lock:
            live = [s for s in self.server._sessions.values()
                    if s.sid in self.server._slots
                    and not s.is_terminal()]
        for s in live:
            s.preempt_event.set()
        deadline = time.perf_counter() + float(grace_s)
        while time.perf_counter() < deadline:
            with self.server._lock:
                if self.server._running == 0:
                    break
            time.sleep(0.02)
        self.close(timeout=0.5)
