# `python -m mpisppy_tpu ...` == the generic_cylinders driver
# (ref:mpisppy/generic_cylinders.py run as a script).
from mpisppy_tpu.generic_cylinders import main

main()
