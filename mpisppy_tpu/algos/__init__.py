# algos subpackage of mpisppy_tpu
