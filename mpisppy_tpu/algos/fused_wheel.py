###############################################################################
# Fused hub-and-spoke wheel step.
#
# The reference runs hub and spokes CONCURRENTLY on disjoint MPI ranks
# (ref:mpisppy/spin_the_wheel.py:224-242 _make_comms;
# ref:mpisppy/cylinders/hub.py:379-445 RMA windows), so spoke wall-clock
# is nearly free.  On one TPU chip every cylinder shares a single device
# queue — separate dispatches SERIALIZE, and a to-convergence Lagrangian
# or xhat solve per sync costs hundreds of times the hub iteration it
# decorates (measured 642x in round 3, BENCH_DETAIL.json).
#
# The TPU-native answer is fusion, not concurrency: the Lagrangian bound
# is the SAME subproblem kernel with W frozen and no prox, and the xhat
# recourse evaluation is the SAME kernel with the nonant box collapsed —
# so both ride inside the hub's single jitted step as fixed small
# restart-window budgets with WARM state carried across iterations.
# Per-iteration device cost becomes
#     (subproblem_windows + lag_windows + xhat_windows) restart windows
# ~ 2-3x bare PH, while the warm states converge across iterations just
# like the reference's continuously-running spoke processes.  Bounds are
# still gated by the same certificates as the standalone spokes
# (dual-residual for the Lagrangian, primal-residual feasibility for
# xhat), so nothing uncertified ever enters the gap.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.algos import aph as aph_mod
from mpisppy_tpu.algos import lagrangian as lag_mod
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.algos import xhat as xhat_mod
from mpisppy_tpu.core.batch import ScenarioBatch, concretize
from mpisppy_tpu.ops import boxqp, pdhg

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FusedWheelOptions:
    """Static per-iteration budgets for the fused spoke plane.

    A window is `restart_period` PDHG iterations; the defaults add
    ~2x the hub's own subproblem work per iteration.  The xhat profile
    uses omega0=0.1 / restart_period=80: the stalled-tail cure measured
    in round 3 (algos/xhat._RESCUE_TIERS) applied from the start, so the
    in-loop evaluation rarely needs a blocking rescue."""

    lag_windows: int = 8
    xhat_windows: int = 4
    slam_windows: int = 0        # 0 = slam plane disabled
    slam_sense_max: bool = True  # ref slam_heuristic max/min variants
    shuffle_windows: int = 0     # 0 = shuffle plane disabled
    # run the spoke planes only every spoke_period-th iteration (two
    # compiled variants, host-alternated) — the fused analog of the
    # hub's spoke_sync_period: bound freshness lags at most
    # spoke_period iterations, per-iteration cost amortizes by 1/p
    spoke_period: int = 1
    # Dispatch each plane as its OWN async device program instead of
    # one monolithic jit.  Measured on v5e at S=10k: the monolithic
    # 4-plane program costs +428 ms/iter over bare PH while the same
    # planes as separate dispatches cost +198 ms — XLA interleaves the
    # data-independent window loops and they evict each other's
    # VMEM-resident state, and async dispatch already hides the ~6 ms
    # tunnel latency.  Split mode is also what makes per-plane adaptive
    # budgets cheap (one small recompile per plane/budget pair).
    # None = AUTO: split at >=512 scenarios; below that per-dispatch
    # overhead dominates device time and the monolithic program wins
    # (uc at S=100: 0.33 s/iter monolithic vs 0.72 s/iter split,
    # measured).  True/False forces.
    split_dispatch: bool | None = None
    # Adaptive budgets (split mode only): a plane runs its full budget
    # until it has CERTIFIED (dual-residual / feasibility gate) for
    # `adapt_stall` consecutive exchanges — its warm solver is then
    # tracking its slowly moving target and the lean budget keeps it
    # certified; any uncertified exchange snaps it back to full.  The
    # certificates are identical either way — budgets only change how
    # fast the warm solver tracks, never what gets certified.
    adapt_budgets: bool = True
    # The Lagrangian plane does NOT lean by default: the outer bound's
    # QUALITY (not just its certificate) gates termination, and on
    # models with fast-moving duals (uc at rho=1000) a lean budget
    # tracks well enough to certify while the bound value lags —
    # measured: uc stalled at 2.5% with lag leaning vs 1.0% certified
    # without, while sslp's headline was unaffected by full lag
    # budgets.  Inner/heuristic planes keep leaning (their freshness
    # only delays incumbent discovery, never weakens a published bound).
    adapt_lag_budget: bool = False
    lean_lag_windows: int = 2
    lean_xhat_windows: int = 1
    lean_slam_windows: int = 1
    lean_shuffle_windows: int = 1
    adapt_stall: int = 3
    # Candidate FREEZING for the x̄ plane (split mode): the evaluated
    # candidate stays frozen across exchanges until it lands (publishes
    # feasible) or xhat_give_up exchanges pass, and only then does the
    # plane adopt a fresh round(x̄).  Without this the candidate churns
    # every exchange and the straggler scenarios' recourse solves never
    # accumulate enough iterations to clear the all-scenario feasibility
    # gate — measured on sslp-10k: 0/90 exchanges published and the
    # 80-second blocking rescue did all the inner-bound work.
    xhat_give_up: int = 25
    # In-loop STRAGGLER TAIL sub-solve: after the main fixed-budget
    # pass, gather the xhat_tail_k worst-primal-residual scenarios into
    # a tiny sub-batch and run them xhat_tail_windows windows at the
    # tier-2 rescue profile (omega0=0.03, restart_period=160), then
    # scatter the state back.  ~0.1-0.3% of sslp recourse LPs are
    # degenerate and need O(100k) PDHG iterations (measured r3/r5) —
    # on the full 10k batch that was only reachable by an 80-second
    # blocking rescue, but on a 64-scenario gather it costs ~1% of a
    # hub step per exchange and accumulates across exchanges on the
    # frozen candidate.  0 disables.
    xhat_tail_k: int = 64
    xhat_tail_windows: int = 12
    lag_pdhg: pdhg.PDHGOptions = pdhg.PDHGOptions(
        tol=1e-6, restart_period=40)
    xhat_pdhg: pdhg.PDHGOptions = pdhg.PDHGOptions(
        tol=1e-6, omega0=0.1, restart_period=80)
    xhat_feas_tol: float = 1e-3
    # max first-order infeasibility compensation (relative to the
    # value) a published inner bound may carry — see _eval_step
    xhat_comp_tol: float = 2e-3


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["ph", "lag_solver", "lag_bound", "lag_certified",
                 "xhat_solver", "xhat_cand", "xhat_value", "xhat_feasible",
                 "xhat_dead",
                 "slam_solver", "slam_cand", "slam_value", "slam_feasible",
                 "shuf_solver", "shuf_cand", "shuf_value", "shuf_feasible",
                 "scalars"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class FusedWheelState:
    ph: ph_mod.PHState
    lag_solver: pdhg.PDHGState   # warm iterates for L(W)
    lag_bound: Array             # () latest E[dual] at W
    lag_certified: Array         # () bool: dual residuals cleared tol
    xhat_solver: pdhg.PDHGState  # warm iterates for the recourse eval
    xhat_cand: Array             # (num_nodes, N) candidate evaluated
    xhat_value: Array            # () E[f(xhat)]; +inf unless feasible
    xhat_feasible: Array         # () bool
    xhat_dead: Array             # () bool: some scenario CERTIFIED
    #                              infeasible/unbounded at this candidate
    slam_solver: pdhg.PDHGState  # warm iterates for the slam candidate
    slam_cand: Array             # (N,) slammed candidate
    slam_value: Array            # ()
    slam_feasible: Array         # () bool
    shuf_solver: pdhg.PDHGState  # warm iterates for the shuffle candidate
    shuf_cand: Array             # (N,) candidate (one scenario's nonants)
    shuf_value: Array            # ()
    shuf_feasible: Array         # () bool
    # (10,) f32 — see SCALAR_KEYS for the layout: every per-iteration
    # host decision packed into ONE device array so the hub pays ONE
    # device->host transfer per iteration (the axon tunnel charges a
    # full round trip per scalar read — ~10 reads/iter measurably
    # dominated wall-clock at small scale)
    scalars: Array


def _lag_step(batch: ScenarioBatch, W: Array, solver: pdhg.PDHGState,
              wopts: FusedWheelOptions, windows: int | None = None):
    """Advance the Lagrangian solve a fixed budget and certify the bound
    (same math as algos.lagrangian.lagrangian_bound, truncated)."""
    qp = lag_mod._lagrangian_qp(batch, W)
    n_win = wopts.lag_windows if windows is None else windows
    st = pdhg.solve_fixed(qp, n_win, wopts.lag_pdhg, solver)
    dual = boxqp.dual_objective(qp, st.x, st.y)
    _, rd, _ = boxqp.kkt_residuals(qp, st.x, st.y)
    tol = jnp.maximum(wopts.lag_pdhg.tol,
                      5.0 * jnp.finfo(st.x.dtype).eps)
    real = batch.p > 0.0
    certified = jnp.all(jnp.where(real, rd <= 10.0 * tol, True))
    return st, batch.expectation(dual), certified


def _gather_scen(tree, idx, S: int):
    """Index the leading scenario axis of every (S, ...)-shaped leaf.
    Safe for PDHGState (every array field is (S, ...) or a () scalar by
    construction); do NOT use on a BoxQP — see _gather_qp."""
    return jax.tree_util.tree_map(
        lambda a: a[idx] if (getattr(a, "ndim", 0) > 0
                             and a.shape[0] == S) else a, tree)


def _gather_qp(qp, idx, S: int):
    """Scenario-gather a BoxQP by FIELD LAYOUT, not dim-size guessing:
    a shared dense A is (m, n), and a model with m == S would trip a
    naive shape[0]-equals-S test into gathering the matrix by scenario
    index (wrong contraction downstream).  The same rule holds inside
    an EllMatrix: only a batched vals (S, m, k) is gathered — cols is
    a shared (m, k) index array whose leading dim is m, never a
    scenario axis (a tree_map over S-sized leading dims would silently
    corrupt it whenever m == S)."""
    def vec(a):       # c/q/l/u: (S, n) batched or (n,) shared
        return a[idx] if a.ndim == 2 else a

    A = qp.A
    if hasattr(A, "vals"):        # EllMatrix: gather by field layout
        if A.vals.ndim == 3:      # batched vals (S, m, k)
            A = dataclasses.replace(A, vals=A.vals[idx])
        # shared vals (m, k): keep; cols is NEVER scenario-indexed
    elif A.ndim == 3:             # per-scenario dense (S, m, n)
        A = A[idx]
    # else shared dense (m, n): keep
    return dataclasses.replace(
        qp, c=vec(qp.c), q=vec(qp.q), l=vec(qp.l), u=vec(qp.u),
        bl=vec(qp.bl), bu=vec(qp.bu), A=A)


def _scatter_scen(tree, sub, idx, S: int):
    """Write a gathered sub-tree back into the (S, ...) leaves."""
    return jax.tree_util.tree_map(
        lambda a, b: (a.at[idx].set(b)
                      if (getattr(a, "ndim", 0) > 0 and a.shape[0] == S)
                      else a), tree, sub)


def _tail_rescue(qp, st: pdhg.PDHGState, rp: Array, real: Array,
                 wopts: FusedWheelOptions,
                 feas_tol: float) -> pdhg.PDHGState:
    """In-loop straggler sub-solve (see FusedWheelOptions.xhat_tail_k):
    top-k worst residual scenarios get a large extra budget at the
    tier-2 rescue profile on a gathered sub-batch, state scattered
    back.  Runs inside the same jitted plane program.

    k is additionally capped at S/8: at small scenario counts a fixed
    64 would re-solve most of the batch (observed: 64 of uc's 100
    scenarios, ~0.7x the hub step, every exchange).  The whole
    sub-solve is lax.cond-gated on some real scenario actually missing
    tolerance, so exchanges whose main pass already cleared the gate
    pay nothing.

    k is quantized DOWN the dispatch bucket ladder — the CONFIGURED
    scheduler's ladder when one exists (--dispatch-bucket-growth
    governs both the oracle megabatches and these gathers), else the
    default: the gathered sub-batch is a fresh device shape per
    distinct k, and without quantization every S (10k sweep, padded
    variants, multi-model processes) mints its own tail executable —
    with it, all of them land on a handful of rungs and the jit cache
    stays bounded (docs/dispatch.md)."""
    from mpisppy_tpu import dispatch as _dispatch
    S = st.omega.shape[0]
    k = min(wopts.xhat_tail_k, max(8, S // 8), S)
    if k > 0:
        sched = _dispatch.get_scheduler(create=False)
        ladder = sched.ladder if sched is not None \
            else _dispatch.default_ladder()
        k = min(ladder.bucket_floor(k), S)
    if k <= 0 or wopts.xhat_tail_windows <= 0:
        return st

    def run(st):
        _, idx = jax.lax.top_k(jnp.where(real, rp, -1.0), k)
        sub_qp = _gather_qp(qp, idx, S)
        sub_st = _gather_scen(st, idx, S)
        topts = dataclasses.replace(
            wopts.xhat_pdhg, omega0=0.03, restart_period=160)
        sub_st = dataclasses.replace(
            sub_st, omega=jnp.full_like(sub_st.omega, topts.omega0))
        sub_st = pdhg.solve_fixed(sub_qp, wopts.xhat_tail_windows, topts,
                                  sub_st)
        return _scatter_scen(st, sub_st, idx, S)

    # engage only while some scenario actually MISSES the publication
    # gate — the tail exists to converge the straggler recourse LPs
    # that block all-scenario feasibility (sslp-10k), not to polish
    # already-feasible solves.  An always-on variant (engage at
    # feas_tol/100) cost uc 0.4 s/iteration for identical bounds,
    # measured: 427 iterations certified the same outer/inner with the
    # tail never improving anything.
    needed = jnp.any(jnp.where(real, rp > feas_tol, False))
    return jax.lax.cond(needed, run, lambda s: s, st)


def _eval_step(batch: ScenarioBatch, cand: Array,
               solver: pdhg.PDHGState, windows: int,
               wopts: FusedWheelOptions, tail: bool = False):
    """Advance the recourse evaluation of a fixed candidate a fixed
    budget.  The candidate moves every iteration, but consecutive
    candidates differ little, so the warm iterates (clipped into the new
    fixed box) track it — the fused analog of XhatXbarInnerBound's warm
    PDHG state.  Validity: the value only counts when EVERY real
    scenario's primal residual clears feas_tol, so a truncated or
    genuinely infeasible solve can never produce an incumbent.

    The published value is COMPENSATED for residual infeasibility: an
    rp-infeasible x can undershoot the true recourse optimum by up to
    ~|y*|'viol (first order), so COMP_SAFETY * E[sum_i |y_i| viol_i] is
    added before publication.  The reference never needs this (Gurobi
    returns exactly feasible candidates, ref:mpisppy/spopt.py:884); a
    truncated first-order solve does, or lean warm budgets can publish
    inner bounds below the optimum (observed on farmer: 8e-4 relative
    leak).  Exactly feasible solves pay zero.  Because the compensation
    reads the CURRENT truncated-solve dual iterate rather than a
    verified dual bound, the exact-penalty inequality holds only to
    first order — the safety factor (xhat.COMP_SAFETY) covers the
    inexact-dual slack, and the published inner bounds are
    APPROXIMATELY certified with error O(rp * |y - y*|); the
    comp-tightness gate below keeps that error a vanishing fraction of
    the value."""
    qp = batch.with_fixed_nonants(cand)
    st = dataclasses.replace(solver, x=jnp.clip(solver.x, qp.l, qp.u))
    # detect_infeas: a candidate that leaves ANY scenario without
    # feasible recourse gets a Farkas certificate within a few windows;
    # the host reads the `dead` flag and adopts a fresh candidate next
    # exchange instead of burning xhat_give_up exchanges (or an
    # 80-second blocking rescue, both observed on sslp-10k) on it.
    popts = dataclasses.replace(wopts.xhat_pdhg, detect_infeas=True)
    st = pdhg.solve_fixed(qp, windows, popts, st)
    real = batch.p > 0.0
    if tail:
        # straggler sub-solve: x-hat plane only — the slam/shuffle
        # planes rotate candidates and must stay cheap
        rp0, _, _ = boxqp.kkt_residuals(qp, st.x, st.y)
        st = _tail_rescue(qp, st, rp0, real, wopts, wopts.xhat_feas_tol)
    obj = jnp.sum(qp.c * st.x + 0.5 * qp.q * st.x * st.x, axis=-1)
    viol = boxqp.primal_residual(qp, st.x)
    comp = xhat_mod.COMP_SAFETY * jnp.sum(jnp.abs(st.y) * viol, axis=-1)
    obj = obj + comp
    rp, _, _ = boxqp.kkt_residuals(qp, st.x, st.y)
    bad_status = (st.status == pdhg.INFEASIBLE) \
        | (st.status == pdhg.UNBOUNDED)
    ok = (rp <= wopts.xhat_feas_tol) & ~bad_status
    feas = jnp.all(jnp.where(real, ok, True))
    dead = jnp.any(jnp.where(real, bad_status, False))
    value = jnp.where(feas, batch.expectation(obj),
                      jnp.asarray(jnp.inf, obj.dtype))
    # TIGHTNESS gate: the compensation is first-order, so a value whose
    # compensation is a material fraction of the bound itself is not
    # trustworthy (hydro measured +37% at stiff duals).  Feasible-but-
    # loose evaluations stay unpublished until the warm solver (or the
    # tail rescue, which engages on rp > feas_tol) tightens them.
    ecomp = batch.expectation(comp)
    tight = ecomp <= wopts.xhat_comp_tol * jnp.maximum(1.0,
                                                       jnp.abs(value))
    feas = feas & tight
    value = jnp.where(feas, value, jnp.asarray(jnp.inf, obj.dtype))
    return st, value, feas, dead


@partial(jax.jit, static_argnames=("opts", "wopts"))
def fused_iter0(batch: ScenarioBatch, rho: Array, opts: ph_mod.PHOptions,
                wopts: FusedWheelOptions):
    """PH Iter0 plus spoke-plane state init.  Both spoke solvers warm
    from the iter0 iterates (same A, so Lnorm/omega carry) — no extra
    power iterations, no cold starts."""
    batch = concretize(batch)  # scengen: synthesize in-trace
    phst, tb, cert = ph_mod.ph_iter0(batch, rho, opts)
    solver = phst.solver
    dt = batch.qp.c.dtype
    if solver.counters is not None:
        # the planes warm-start from the hub's iter0 ITERATES, but
        # their kernel counters must start at zero — copying the hub's
        # iter0 totals would inflate every cyl-labeled plane metric by
        # the full iter0 count (and multi-count it across planes)
        from mpisppy_tpu.telemetry import counters as _kc
        solver = dataclasses.replace(
            solver, counters=_kc.init_counters(
                solver.omega.shape, dt,
                ring_size=solver.counters.ring.shape[-1]))
    xhat_solver = dataclasses.replace(
        solver, omega=jnp.full_like(solver.omega, wopts.xhat_pdhg.omega0))
    st = FusedWheelState(
        ph=phst,
        lag_solver=solver,
        lag_bound=jnp.asarray(-jnp.inf, dt),
        lag_certified=jnp.asarray(False),
        xhat_solver=xhat_solver,
        xhat_cand=jnp.zeros((batch.tree.num_nodes, batch.num_nonants), dt),
        xhat_value=jnp.asarray(jnp.inf, dt),
        xhat_feasible=jnp.asarray(False),
        xhat_dead=jnp.asarray(False),
        slam_solver=xhat_solver,
        slam_cand=jnp.zeros((batch.num_nonants,), dt),
        slam_value=jnp.asarray(jnp.inf, dt),
        slam_feasible=jnp.asarray(False),
        shuf_solver=xhat_solver,
        shuf_cand=jnp.zeros((batch.num_nonants,), dt),
        shuf_value=jnp.asarray(jnp.inf, dt),
        shuf_feasible=jnp.asarray(False),
        scalars=jnp.zeros((10,), dt),
    )
    return dataclasses.replace(st, scalars=_pack_scalars(st)), tb, cert


def _pack_scalars(st: "FusedWheelState") -> Array:
    dt = st.ph.conv.dtype
    return jnp.stack([
        st.ph.conv.astype(dt),
        st.lag_bound.astype(dt),
        st.lag_certified.astype(dt),
        st.xhat_value.astype(dt),
        st.xhat_feasible.astype(dt),
        st.xhat_dead.astype(dt),
        st.slam_value.astype(dt),
        st.slam_feasible.astype(dt),
        st.shuf_value.astype(dt),
        st.shuf_feasible.astype(dt),
    ])


SCALAR_KEYS = ("conv", "lag_bound", "lag_certified", "xhat_value",
               "xhat_feasible", "xhat_dead", "slam_value",
               "slam_feasible", "shuf_value", "shuf_feasible")

# How many exchanges the pipelined scalar cache lags the dispatched
# iterate (FusedPH._cache_scalars reads the PREVIOUS iteration's packed
# scalars, which themselves describe the step before it).  Every host
# decision that attributes cached flags to a candidate must wait this
# many evaluations — _iterk_split's flags_fresh references this
# constant so a pipelining change cannot silently misattribute
# landed/dead flags (double rotation, skipped rounding tiers).
SCALAR_PIPELINE_DEPTH = 2


@partial(jax.jit, static_argnames=("opts", "wopts"))
def fused_iterk(batch: ScenarioBatch, st: FusedWheelState,
                opts: ph_mod.PHOptions, wopts: FusedWheelOptions,
                shuf_id: Array | None = None) -> FusedWheelState:
    """One wheel iteration as ONE compiled program: hub PH step, then
    the Lagrangian bound at the fresh W and the recourse values at the
    fresh candidates (rounded x̄ / slam / shuffled scenario), each a
    fixed warm budget."""
    batch = concretize(batch)  # scengen: synthesize in-trace
    phst = ph_mod.ph_iterk(batch, st.ph, opts)
    out = dataclasses.replace(st, ph=phst)

    # The planes are data-independent given phst, so XLA freely
    # interleaves their window loops — measured on v5e at S=10k this is
    # strongly superadditive (individual plane extras sum to 240 ms but
    # the 4-plane program costs +428 ms: interleaved loops evict each
    # other's VMEM-resident solver state).  `fence` threads each
    # plane's warm inputs through an optimization_barrier with the
    # previous plane's outputs, forcing the planes to run one after
    # another, each with the VMEM to itself.
    done_vals = [phst]

    def fence(*vals):
        fenced = jax.lax.optimization_barrier(tuple(done_vals) + vals)
        return fenced[len(done_vals):]

    if wopts.lag_windows > 0:
        (lag_in,) = fence(st.lag_solver)
        lag_solver, lag_bound, lag_cert = _lag_step(
            batch, phst.W, lag_in, wopts)
        out = dataclasses.replace(out, lag_solver=lag_solver,
                                  lag_bound=lag_bound,
                                  lag_certified=lag_cert)
        done_vals.append(lag_solver)
    if wopts.xhat_windows > 0:
        cand = xhat_mod.round_integers(batch, phst.xbar_nodes)
        (xhat_in,) = fence(st.xhat_solver)
        xs, value, feas, dead = _eval_step(batch, cand, xhat_in,
                                           wopts.xhat_windows, wopts,
                                           tail=True)
        out = dataclasses.replace(out, xhat_solver=xs, xhat_cand=cand,
                                  xhat_value=value, xhat_feasible=feas,
                                  xhat_dead=dead)
        done_vals.append(xs)
    if wopts.slam_windows > 0 or wopts.shuffle_windows > 0:
        x_non = batch.nonants(phst.solver.x)
    if wopts.slam_windows > 0:
        scand = xhat_mod.slam_candidate(batch, x_non, wopts.slam_sense_max)
        (slam_in,) = fence(st.slam_solver)
        ss, svalue, sfeas, _ = _eval_step(batch, scand, slam_in,
                                          wopts.slam_windows, wopts)
        out = dataclasses.replace(out, slam_solver=ss, slam_cand=scand,
                                  slam_value=svalue, slam_feasible=sfeas)
        done_vals.append(ss)
    if wopts.shuffle_windows > 0:
        # one rotating candidate per iteration (the host supplies the
        # deterministic shuffle index, seed 42 — ref:
        # xhatshufflelooper_bounder.py:74); over a run this visits
        # scenarios' own first stages like the reference's looper
        sid = jnp.asarray(0, jnp.int32) if shuf_id is None else shuf_id
        fcand = xhat_mod.round_integers(batch, x_non[sid])
        (shuf_in,) = fence(st.shuf_solver)
        fs, fvalue, ffeas, _ = _eval_step(batch, fcand, shuf_in,
                                          wopts.shuffle_windows, wopts)
        out = dataclasses.replace(out, shuf_solver=fs, shuf_cand=fcand,
                                  shuf_value=fvalue, shuf_feasible=ffeas)
    return dataclasses.replace(out, scalars=_pack_scalars(out))


# --- async exchange plane (ISSUE 11 tentpole; docs/async_wheel.md) ----
# One slot of the double-buffered host<->device exchange plane: the
# W/x̄/iterate view the spoke planes and the stale-prox hub step read at
# iteration k while the host completes the exchange for an earlier
# iteration.  Slots hold DEVICE REFS (arrays are immutable; a "plane
# write" is a host-side pointer swap, never a transfer), so the ring in
# algos/async_wheel.AsyncFusedPH costs no HBM beyond the generations it
# pins alive.

@partial(
    jax.tree_util.register_dataclass,
    data_fields=["W", "xbar", "xbar_nodes", "x"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class ExchangePlane:
    W: Array           # (S, N) duals at the plane's generation
    xbar: Array        # (S, N) per-scenario view of node averages
    xbar_nodes: Array  # (num_nodes, N)
    x: Array           # (S, n) full primal iterates (slam/shuf inputs)


def plane_of(phst: ph_mod.PHState) -> ExchangePlane:
    """The exchange-plane view of one PH state generation."""
    return ExchangePlane(W=phst.W, xbar=phst.xbar,
                         xbar_nodes=phst.xbar_nodes, x=phst.solver.x)


@partial(jax.jit, static_argnames=("opts", "nu", "gamma", "theta_floor"))
def ph_stale_step(batch: ScenarioBatch, st: ph_mod.PHState,
                  plane: ExchangePlane, opts: ph_mod.PHOptions,
                  nu: float = 1.0, gamma: float = 1.0,
                  theta_floor: float = 0.05):
    """One theta-damped PH hub step against a (possibly stale) exchange
    plane — the APH-class stale-plane hub iteration (ISSUE 11;
    docs/async_wheel.md).

    The subproblem proxes around the PLANE's x̄ (the center the device
    can form without waiting for the host exchange) instead of the
    state's own freshest average; the multiplier update is then damped
    by the APH projective step length (algos/aph.projective_theta):

        W_new = W + theta * rho * (x_new - x̄_new),  theta in [floor, 1]

    At plane == the previous iteration's output and theta == 1 this is
    EXACTLY ph_iterk (synchronous PH already proxes around the previous
    x̄), so staleness-1 deviates from the synchronous trajectory only by
    the damping; deeper staleness lags the prox center further, and
    theta contracts automatically when the stale direction stops making
    projective progress.  Returns (new_state, theta)."""
    batch = concretize(batch)  # scengen: synthesize in-trace
    smooth_p = opts.smooth_p if opts.smoothed else 0.0
    qp_eff = ph_mod._prox_qp(batch, st.W, plane.xbar, st.z, st.rho,
                             smooth_p)
    solver = pdhg.solve_fixed(qp_eff, opts.subproblem_windows, opts.pdhg,
                              st.solver)
    st2 = dataclasses.replace(st, solver=solver)
    x_non, xbar, xbar_nodes, xsqbar, W_full, z, conv = ph_mod._xbar_w_conv(
        batch, st2, opts.smooth_beta, opts.smoothed, opts.compute_xsqbar)
    theta = aph_mod.projective_theta(batch, x_non, xbar, st.W, plane.xbar,
                                     plane.W, st.rho, nu, gamma)
    # floor: near convergence phi ~ ||x - z_plane||^2 -> 0 would freeze
    # the duals entirely; a small floor keeps the (already tiny) PH
    # update flowing (docs/async_wheel.md theta-damping rationale)
    theta = jnp.maximum(theta, jnp.asarray(theta_floor, theta.dtype))
    # W_full is st.W + rho*(x - xbar) (masked for var_prob batches by
    # _xbar_w_conv), so blending recovers the damped update exactly
    W = st.W + theta * (W_full - st.W)
    out = dataclasses.replace(st2, W=W, z=z, xbar=xbar,
                              xbar_nodes=xbar_nodes, xsqbar=xsqbar,
                              conv=conv)
    return out, theta


# --- split-dispatch plane programs -----------------------------------
# Each plane as its own small jitted program (see
# FusedWheelOptions.split_dispatch).  `windows` is static: the adaptive
# controller only ever uses the {full, lean} pair per plane, so at most
# two compiles per plane exist per run.

@partial(jax.jit, static_argnames=("wopts", "windows"))
def lag_plane(batch, W, solver, wopts, windows):
    return _lag_step(concretize(batch), W, solver, wopts, windows)


@partial(jax.jit, static_argnames=("mode",))
def _round_xbar(batch, xbar_nodes, mode="nearest"):
    return xhat_mod.round_integers(concretize(batch), xbar_nodes, mode)


@partial(jax.jit, static_argnames=("wopts", "windows"))
def xhat_plane(batch, cand, solver, wopts, windows):
    st, value, feas, dead = _eval_step(concretize(batch), cand, solver,
                                       windows, wopts, tail=True)
    return st, value, feas, dead


@partial(jax.jit, static_argnames=("wopts", "windows", "sense_max"))
def slam_plane(batch, x, solver, wopts, windows, sense_max):
    batch = concretize(batch)
    x_non = batch.nonants(x)
    scand = xhat_mod.slam_candidate(batch, x_non, sense_max)
    st, value, feas, _ = _eval_step(batch, scand, solver, windows, wopts)
    return st, scand, value, feas


@partial(jax.jit, static_argnames=("wopts", "windows"))
def shuf_plane(batch, x, solver, sid, wopts, windows):
    batch = concretize(batch)
    x_non = batch.nonants(x)
    fcand = xhat_mod.round_integers(batch, x_non[sid])
    st, value, feas, _ = _eval_step(batch, fcand, solver, windows, wopts)
    return st, fcand, value, feas


@jax.jit
def _pack_scalars_jit(st: "FusedWheelState") -> Array:
    return _pack_scalars(st)


class _PlaneBudget:
    """Host-side controller driving one plane's {full, lean} budget off
    its CERTIFICATION streak.

    Rationale: once a plane's warm solver certifies (dual residual for
    the Lagrangian, primal feasibility for the candidate evaluations)
    for `stall_after` consecutive exchanges, it is tracking its slowly
    moving target and a lean budget keeps it certified; the moment
    certification is lost the budget snaps back to full.  Validity is
    unaffected either way — certificates gate every published value
    identically at any budget; lean can only cost bound freshness,
    and an under-budgeted plane immediately reveals itself by failing
    to certify (which restores the full budget)."""

    def __init__(self, full: int, lean: int, stall_after: int):
        self.full = full
        self.lean = max(1, min(lean, full)) if full > 0 else 0
        self.stall_after = stall_after
        self.streak = 0

    def windows(self) -> int:
        if self.full <= 0:
            return 0
        return self.lean if self.streak >= self.stall_after else self.full

    def observe(self, certified: bool) -> None:
        self.streak = self.streak + 1 if certified else 0


class FusedPH(ph_mod.PH):
    """PH driver whose iteration IS the whole wheel step.

    Use with the Fused* spoke classes (cylinders.spoke): they read
    bounds off `self.wstate` instead of launching their own device
    work.  Classic spokes still work alongside (the hub updates them on
    its sync period as before)."""

    def __init__(self, options, batch, wheel_options=None, **kw):
        super().__init__(options, batch, **kw)
        self.wheel_options = wheel_options or FusedWheelOptions()
        self.wstate: FusedWheelState | None = None
        self.scalar_cache: dict | None = None
        self.cand_cache: dict | None = None
        self._scalars_inflight = None
        self._shuf_order = np.random.default_rng(42).permutation(
            batch.num_real)
        self._shuf_cursor = 0
        self._xhat_frozen_for = 0
        self._xhat_has_cand = False
        self._xhat_round_mode = "nearest"
        w = self.wheel_options
        stall = w.adapt_stall if w.adapt_budgets else (1 << 30)
        lag_stall = stall if w.adapt_lag_budget else (1 << 30)
        self._budgets = {
            "lag": _PlaneBudget(w.lag_windows, w.lean_lag_windows,
                                lag_stall),
            "xhat": _PlaneBudget(w.xhat_windows, w.lean_xhat_windows,
                                 stall),
            "slam": _PlaneBudget(w.slam_windows, w.lean_slam_windows,
                                 stall),
            "shuf": _PlaneBudget(w.shuffle_windows,
                                 w.lean_shuffle_windows, stall),
        }

    def _cache_scalars(self, pipelined: bool = False):
        """ONE device->host transfer per iteration: everything the hub
        and the fused spokes decide on.  Pipelined mode reads the
        PREVIOUS iteration's packed scalars right after dispatching the
        next step (total read lag: SCALAR_PIPELINE_DEPTH exchanges), so
        the host never blocks on the in-flight program —
        the hub's decisions lag one iteration (bounds are valid at every
        iterate, so a one-iteration-late termination is still certified;
        this is exactly the reference's stale-window tolerance,
        ref:cylinders/hub.py write-id freshness).  The candidate tensors
        ride the same pipeline so a cached value is always paired with
        the candidate it was evaluated at."""
        inflight = (self.wstate.scalars, self.wstate.xhat_cand,
                    self.wstate.slam_cand, self.wstate.shuf_cand)
        if pipelined and self._scalars_inflight is not None:
            scalars, xc, sc_, fc = self._scalars_inflight
        else:
            scalars, xc, sc_, fc = inflight
        self._scalars_inflight = inflight
        # the ONE place the hub loop blocks on the mesh: with an
        # elastic MeshRuntime armed (parallel/elastic.py) the fetch is
        # deadline-bounded and chaos-seamed — a straggler or lost host
        # trips a typed MeshDegraded here instead of hanging the hub;
        # without one, the plain fetch below is the whole cost
        spcomm = getattr(self, "spcomm", None)
        rt = None if spcomm is None \
            else spcomm.options.get("mesh_runtime")
        if rt is not None:
            vals = rt.harvest(lambda: np.asarray(scalars),
                              hub_iter=self._iter)
        else:
            vals = np.asarray(scalars)
        self.scalar_cache = dict(zip(SCALAR_KEYS, (float(v) for v in vals)))
        # device refs, transferred only when a spoke actually offers
        self.cand_cache = {"xhat": xc, "slam": sc_, "shuf": fc}

    def flush_scalars(self):
        """Synchronize the cache to the LATEST iterate (final harvest)."""
        if self.wstate is not None:
            self._cache_scalars()

    def _read_conv(self) -> float:
        return self.scalar_cache["conv"]

    def state_template(self):
        st, _, _ = jax.eval_shape(
            partial(fused_iter0, opts=ph_mod.kernel_opts(self.options),
                    wopts=self.wheel_options),
            self.batch, self.rho)
        return st

    def _iter0_impl(self):
        self.wstate, tb, cert = fused_iter0(
            self.batch, self.rho, ph_mod.kernel_opts(self.options),
            self.wheel_options)
        self._cache_scalars()
        return self.wstate.ph, tb, cert

    def _draw_spoke_cycle(self):
        """Advance the shuffle cursor one draw and evaluate the spoke
        cadence for this iteration — the ONE place the (sid, spoke_iter)
        pair comes from, shared with the async driver's stale path so
        shuffle/cadence semantics can never drift between the two
        iteration paths."""
        sid = jnp.asarray(
            int(self._shuf_order[self._shuf_cursor]), jnp.int32)
        self._shuf_cursor = (self._shuf_cursor + 1) % len(self._shuf_order)
        p = max(1, int(self.wheel_options.spoke_period))
        return sid, p <= 1 or (self._iter % p) == 0

    def _iterk_impl(self):
        sid, spoke_iter = self._draw_spoke_cycle()
        wopts = self.wheel_options
        split = wopts.split_dispatch
        if split is None:
            split = self.batch.num_real >= 512
        if split:
            self.wstate = self._iterk_split(wopts, sid, spoke_iter)
        else:
            w = wopts
            if not spoke_iter:
                # hub-only variant: spoke planes skipped, their
                # state/bounds carried untouched (harvests re-read last
                # values — folding is idempotent)
                w = dataclasses.replace(
                    w, lag_windows=0, xhat_windows=0, slam_windows=0,
                    shuffle_windows=0)
            # self.state may have been rebound by extensions/convergers
            # (e.g. rho updaters) — fold it back into the wheel state
            self.wstate = fused_iterk(
                self.batch,
                dataclasses.replace(self.wstate, ph=self.state),
                ph_mod.kernel_opts(self.options), w, sid)
        self._cache_scalars(pipelined=True)
        if spoke_iter:
            self._observe_progress()
        return self.wstate.ph

    def _next_xhat_cand(self, xbar_nodes, current_cand):
        """The x̂ plane's freeze/rotate candidate policy, shared by the
        split-dispatch pipeline and the async wheel (which derives
        xbar_nodes from its stale exchange plane).

        The pipelined scalar cache lags SCALAR_PIPELINE_DEPTH
        iterations (see _cache_scalars), so right after an adoption the
        landed/dead flags still describe the PREVIOUS candidate —
        acting on them would rotate twice and skip a rounding tier;
        trust them only once this candidate has been evaluated
        pipeline-depth exchanges."""
        sc = self.scalar_cache or {}
        wopts = self.wheel_options
        flags_fresh = self._xhat_frozen_for >= SCALAR_PIPELINE_DEPTH
        landed = flags_fresh and bool(sc.get("xhat_feasible", 0.0))
        dead = flags_fresh and bool(sc.get("xhat_dead", 0.0))
        give_up = self._xhat_frozen_for >= wopts.xhat_give_up
        if landed or dead or give_up or not self._xhat_has_cand:
            if landed:
                # a landed candidate validates the current rounding
                # direction — keep it
                pass
            elif dead or give_up:
                # escalate the rounding direction: on sslp-like models
                # nearest-rounding strands recourse demand and the
                # candidate is CERTIFIED dead; ceil opens every
                # fractional facility
                order = ("nearest", "ceil", "floor")
                i = order.index(self._xhat_round_mode)
                self._xhat_round_mode = order[(i + 1) % 3]
            cand = _round_xbar(self.batch, xbar_nodes,
                               self._xhat_round_mode)
            self._xhat_frozen_for = 0
            self._xhat_has_cand = True
        else:
            cand = current_cand  # frozen: keep accumulating
            self._xhat_frozen_for += 1
        return cand

    def _iterk_split(self, wopts: FusedWheelOptions, sid,
                     spoke_iter: bool) -> FusedWheelState:
        """One wheel iteration as a PIPELINE of async dispatches: the
        hub PH step, then each enabled plane as its own program, then
        the scalar pack.  Nothing here blocks the host — the device
        queue drains them back-to-back, and the ~6 ms-per-dispatch
        tunnel latency hides behind execution (measured: the monolithic
        fused program is 1.8x slower at S=10k; see split_dispatch)."""
        batch = self.batch
        phst = ph_mod.ph_iterk(batch, self.state,
                               ph_mod.kernel_opts(self.options))
        out = dataclasses.replace(self.wstate, ph=phst)
        if spoke_iter:
            out = self._dispatch_spoke_planes(out, phst.W,
                                              phst.xbar_nodes,
                                              phst.solver.x, sid)
        return dataclasses.replace(out, scalars=_pack_scalars_jit(out))

    def _dispatch_spoke_planes(self, out, W, xbar_nodes, x, sid,
                               dispatch=None):
        """The four spoke-plane dispatches against one (W, x̄-nodes, x)
        view — the current step's outputs on the synchronous split
        path, the stale exchange plane on the async wheel.  `dispatch`
        wraps each plane call (the async wheel routes through
        fire-and-forget PlaneTickets); the default is the direct async
        XLA dispatch."""
        if dispatch is None:
            def dispatch(label, fn, *args):
                return fn(*args)
        wopts = self.wheel_options
        batch = self.batch
        b = self._budgets
        if b["lag"].windows() > 0:
            ls, lb, lc = dispatch("lag", lag_plane, batch, W,
                                  out.lag_solver, wopts,
                                  b["lag"].windows())
            out = dataclasses.replace(
                out, lag_solver=ls, lag_bound=lb, lag_certified=lc)
        if b["xhat"].windows() > 0:
            cand = self._next_xhat_cand(xbar_nodes, out.xhat_cand)
            xs, xv, xf, xd = dispatch("xhat", xhat_plane, batch, cand,
                                      out.xhat_solver, wopts,
                                      b["xhat"].windows())
            out = dataclasses.replace(
                out, xhat_solver=xs, xhat_cand=cand, xhat_value=xv,
                xhat_feasible=xf, xhat_dead=xd)
        if b["slam"].windows() > 0:
            ss, scand, sv, sf = dispatch(
                "slam", slam_plane, batch, x, out.slam_solver, wopts,
                b["slam"].windows(), wopts.slam_sense_max)
            out = dataclasses.replace(
                out, slam_solver=ss, slam_cand=scand, slam_value=sv,
                slam_feasible=sf)
        if b["shuf"].windows() > 0:
            fs, fcand, fv, ff = dispatch(
                "shuf", shuf_plane, batch, x, out.shuf_solver, sid,
                wopts, b["shuf"].windows())
            out = dataclasses.replace(
                out, shuf_solver=fs, shuf_cand=fcand, shuf_value=fv,
                shuf_feasible=ff)
        return out

    def _observe_progress(self):
        """Feed the (possibly one-iteration-stale, see _cache_scalars)
        certification flags to the budget controllers.  Staleness only
        delays a budget switch by one exchange — harmless."""
        sc = self.scalar_cache
        if not sc:
            return
        self._budgets["lag"].observe(bool(sc["lag_certified"]))
        self._budgets["xhat"].observe(bool(sc["xhat_feasible"]))
        self._budgets["slam"].observe(bool(sc["slam_feasible"]))
        self._budgets["shuf"].observe(bool(sc["shuf_feasible"]))
