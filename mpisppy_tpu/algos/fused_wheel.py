###############################################################################
# Fused hub-and-spoke wheel step.
#
# The reference runs hub and spokes CONCURRENTLY on disjoint MPI ranks
# (ref:mpisppy/spin_the_wheel.py:224-242 _make_comms;
# ref:mpisppy/cylinders/hub.py:379-445 RMA windows), so spoke wall-clock
# is nearly free.  On one TPU chip every cylinder shares a single device
# queue — separate dispatches SERIALIZE, and a to-convergence Lagrangian
# or xhat solve per sync costs hundreds of times the hub iteration it
# decorates (measured 642x in round 3, BENCH_DETAIL.json).
#
# The TPU-native answer is fusion, not concurrency: the Lagrangian bound
# is the SAME subproblem kernel with W frozen and no prox, and the xhat
# recourse evaluation is the SAME kernel with the nonant box collapsed —
# so both ride inside the hub's single jitted step as fixed small
# restart-window budgets with WARM state carried across iterations.
# Per-iteration device cost becomes
#     (subproblem_windows + lag_windows + xhat_windows) restart windows
# ~ 2-3x bare PH, while the warm states converge across iterations just
# like the reference's continuously-running spoke processes.  Bounds are
# still gated by the same certificates as the standalone spokes
# (dual-residual for the Lagrangian, primal-residual feasibility for
# xhat), so nothing uncertified ever enters the gap.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.algos import lagrangian as lag_mod
from mpisppy_tpu.algos import ph as ph_mod
from mpisppy_tpu.algos import xhat as xhat_mod
from mpisppy_tpu.core.batch import ScenarioBatch
from mpisppy_tpu.ops import boxqp, pdhg

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FusedWheelOptions:
    """Static per-iteration budgets for the fused spoke plane.

    A window is `restart_period` PDHG iterations; the defaults add
    ~2x the hub's own subproblem work per iteration.  The xhat profile
    uses omega0=0.1 / restart_period=80: the stalled-tail cure measured
    in round 3 (algos/xhat._RESCUE_TIERS) applied from the start, so the
    in-loop evaluation rarely needs a blocking rescue."""

    lag_windows: int = 8
    xhat_windows: int = 4
    slam_windows: int = 0        # 0 = slam plane disabled
    slam_sense_max: bool = True  # ref slam_heuristic max/min variants
    shuffle_windows: int = 0     # 0 = shuffle plane disabled
    # run the spoke planes only every spoke_period-th iteration (two
    # compiled variants, host-alternated) — the fused analog of the
    # hub's spoke_sync_period: bound freshness lags at most
    # spoke_period iterations, per-iteration cost amortizes by 1/p
    spoke_period: int = 1
    lag_pdhg: pdhg.PDHGOptions = pdhg.PDHGOptions(
        tol=1e-6, restart_period=40)
    xhat_pdhg: pdhg.PDHGOptions = pdhg.PDHGOptions(
        tol=1e-6, omega0=0.1, restart_period=80)
    xhat_feas_tol: float = 1e-3


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["ph", "lag_solver", "lag_bound", "lag_certified",
                 "xhat_solver", "xhat_cand", "xhat_value", "xhat_feasible",
                 "slam_solver", "slam_cand", "slam_value", "slam_feasible",
                 "shuf_solver", "shuf_cand", "shuf_value", "shuf_feasible",
                 "scalars"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class FusedWheelState:
    ph: ph_mod.PHState
    lag_solver: pdhg.PDHGState   # warm iterates for L(W)
    lag_bound: Array             # () latest E[dual] at W
    lag_certified: Array         # () bool: dual residuals cleared tol
    xhat_solver: pdhg.PDHGState  # warm iterates for the recourse eval
    xhat_cand: Array             # (num_nodes, N) candidate evaluated
    xhat_value: Array            # () E[f(xhat)]; +inf unless feasible
    xhat_feasible: Array         # () bool
    slam_solver: pdhg.PDHGState  # warm iterates for the slam candidate
    slam_cand: Array             # (N,) slammed candidate
    slam_value: Array            # ()
    slam_feasible: Array         # () bool
    shuf_solver: pdhg.PDHGState  # warm iterates for the shuffle candidate
    shuf_cand: Array             # (N,) candidate (one scenario's nonants)
    shuf_value: Array            # ()
    shuf_feasible: Array         # () bool
    # (9,) f32 [conv, lag_bound, lag_cert, xhat_value, xhat_feas,
    # slam_value, slam_feas, shuf_value, shuf_feas]: every per-iteration
    # host decision packed into ONE device array so the hub pays ONE
    # device->host transfer per iteration (the axon tunnel charges a
    # full round trip per scalar read — ~10 reads/iter measurably
    # dominated wall-clock at small scale)
    scalars: Array


def _lag_step(batch: ScenarioBatch, W: Array, solver: pdhg.PDHGState,
              wopts: FusedWheelOptions):
    """Advance the Lagrangian solve a fixed budget and certify the bound
    (same math as algos.lagrangian.lagrangian_bound, truncated)."""
    qp = lag_mod._lagrangian_qp(batch, W)
    st = pdhg.solve_fixed(qp, wopts.lag_windows, wopts.lag_pdhg, solver)
    dual = boxqp.dual_objective(qp, st.x, st.y)
    _, rd, _ = boxqp.kkt_residuals(qp, st.x, st.y)
    tol = jnp.maximum(wopts.lag_pdhg.tol,
                      5.0 * jnp.finfo(st.x.dtype).eps)
    real = batch.p > 0.0
    certified = jnp.all(jnp.where(real, rd <= 10.0 * tol, True))
    return st, batch.expectation(dual), certified


def _eval_step(batch: ScenarioBatch, cand: Array,
               solver: pdhg.PDHGState, windows: int,
               wopts: FusedWheelOptions):
    """Advance the recourse evaluation of a fixed candidate a fixed
    budget.  The candidate moves every iteration, but consecutive
    candidates differ little, so the warm iterates (clipped into the new
    fixed box) track it — the fused analog of XhatXbarInnerBound's warm
    PDHG state.  Validity: the value only counts when EVERY real
    scenario's primal residual clears feas_tol, so a truncated or
    genuinely infeasible solve can never produce an incumbent."""
    qp = batch.with_fixed_nonants(cand)
    st = dataclasses.replace(solver, x=jnp.clip(solver.x, qp.l, qp.u))
    st = pdhg.solve_fixed(qp, windows, wopts.xhat_pdhg, st)
    obj = jnp.sum(qp.c * st.x + 0.5 * qp.q * st.x * st.x, axis=-1)
    rp, _, _ = boxqp.kkt_residuals(qp, st.x, st.y)
    real = batch.p > 0.0
    ok = rp <= wopts.xhat_feas_tol
    feas = jnp.all(jnp.where(real, ok, True))
    value = jnp.where(feas, batch.expectation(obj),
                      jnp.asarray(jnp.inf, obj.dtype))
    return st, value, feas


@partial(jax.jit, static_argnames=("opts", "wopts"))
def fused_iter0(batch: ScenarioBatch, rho: Array, opts: ph_mod.PHOptions,
                wopts: FusedWheelOptions):
    """PH Iter0 plus spoke-plane state init.  Both spoke solvers warm
    from the iter0 iterates (same A, so Lnorm/omega carry) — no extra
    power iterations, no cold starts."""
    phst, tb, cert = ph_mod.ph_iter0(batch, rho, opts)
    solver = phst.solver
    dt = batch.qp.c.dtype
    xhat_solver = dataclasses.replace(
        solver, omega=jnp.full_like(solver.omega, wopts.xhat_pdhg.omega0))
    st = FusedWheelState(
        ph=phst,
        lag_solver=solver,
        lag_bound=jnp.asarray(-jnp.inf, dt),
        lag_certified=jnp.asarray(False),
        xhat_solver=xhat_solver,
        xhat_cand=jnp.zeros((batch.tree.num_nodes, batch.num_nonants), dt),
        xhat_value=jnp.asarray(jnp.inf, dt),
        xhat_feasible=jnp.asarray(False),
        slam_solver=xhat_solver,
        slam_cand=jnp.zeros((batch.num_nonants,), dt),
        slam_value=jnp.asarray(jnp.inf, dt),
        slam_feasible=jnp.asarray(False),
        shuf_solver=xhat_solver,
        shuf_cand=jnp.zeros((batch.num_nonants,), dt),
        shuf_value=jnp.asarray(jnp.inf, dt),
        shuf_feasible=jnp.asarray(False),
        scalars=jnp.zeros((9,), dt),
    )
    return dataclasses.replace(st, scalars=_pack_scalars(st)), tb, cert


def _pack_scalars(st: "FusedWheelState") -> Array:
    dt = st.ph.conv.dtype
    return jnp.stack([
        st.ph.conv.astype(dt),
        st.lag_bound.astype(dt),
        st.lag_certified.astype(dt),
        st.xhat_value.astype(dt),
        st.xhat_feasible.astype(dt),
        st.slam_value.astype(dt),
        st.slam_feasible.astype(dt),
        st.shuf_value.astype(dt),
        st.shuf_feasible.astype(dt),
    ])


SCALAR_KEYS = ("conv", "lag_bound", "lag_certified", "xhat_value",
               "xhat_feasible", "slam_value", "slam_feasible",
               "shuf_value", "shuf_feasible")


@partial(jax.jit, static_argnames=("opts", "wopts"))
def fused_iterk(batch: ScenarioBatch, st: FusedWheelState,
                opts: ph_mod.PHOptions, wopts: FusedWheelOptions,
                shuf_id: Array | None = None) -> FusedWheelState:
    """One wheel iteration as ONE compiled program: hub PH step, then
    the Lagrangian bound at the fresh W and the recourse values at the
    fresh candidates (rounded x̄ / slam / shuffled scenario), each a
    fixed warm budget."""
    phst = ph_mod.ph_iterk(batch, st.ph, opts)
    out = dataclasses.replace(st, ph=phst)
    if wopts.lag_windows > 0:
        lag_solver, lag_bound, lag_cert = _lag_step(
            batch, phst.W, st.lag_solver, wopts)
        out = dataclasses.replace(out, lag_solver=lag_solver,
                                  lag_bound=lag_bound,
                                  lag_certified=lag_cert)
    if wopts.xhat_windows > 0:
        cand = xhat_mod.round_integers(batch, phst.xbar_nodes)
        xs, value, feas = _eval_step(batch, cand, st.xhat_solver,
                                     wopts.xhat_windows, wopts)
        out = dataclasses.replace(out, xhat_solver=xs, xhat_cand=cand,
                                  xhat_value=value, xhat_feasible=feas)
    if wopts.slam_windows > 0 or wopts.shuffle_windows > 0:
        x_non = batch.nonants(phst.solver.x)
    if wopts.slam_windows > 0:
        scand = xhat_mod.slam_candidate(batch, x_non, wopts.slam_sense_max)
        ss, svalue, sfeas = _eval_step(batch, scand, st.slam_solver,
                                      wopts.slam_windows, wopts)
        out = dataclasses.replace(out, slam_solver=ss, slam_cand=scand,
                                  slam_value=svalue, slam_feasible=sfeas)
    if wopts.shuffle_windows > 0:
        # one rotating candidate per iteration (the host supplies the
        # deterministic shuffle index, seed 42 — ref:
        # xhatshufflelooper_bounder.py:74); over a run this visits
        # scenarios' own first stages like the reference's looper
        sid = jnp.asarray(0, jnp.int32) if shuf_id is None else shuf_id
        fcand = xhat_mod.round_integers(batch, x_non[sid])
        fs, fvalue, ffeas = _eval_step(batch, fcand, st.shuf_solver,
                                       wopts.shuffle_windows, wopts)
        out = dataclasses.replace(out, shuf_solver=fs, shuf_cand=fcand,
                                  shuf_value=fvalue, shuf_feasible=ffeas)
    return dataclasses.replace(out, scalars=_pack_scalars(out))


class FusedPH(ph_mod.PH):
    """PH driver whose iteration IS the whole wheel step.

    Use with the Fused* spoke classes (cylinders.spoke): they read
    bounds off `self.wstate` instead of launching their own device
    work.  Classic spokes still work alongside (the hub updates them on
    its sync period as before)."""

    def __init__(self, options, batch, wheel_options=None, **kw):
        super().__init__(options, batch, **kw)
        self.wheel_options = wheel_options or FusedWheelOptions()
        self.wstate: FusedWheelState | None = None
        self.scalar_cache: dict | None = None
        self.cand_cache: dict | None = None
        self._scalars_inflight = None
        self._shuf_order = np.random.default_rng(42).permutation(
            batch.num_real)
        self._shuf_cursor = 0

    def _cache_scalars(self, pipelined: bool = False):
        """ONE device->host transfer per iteration: everything the hub
        and the fused spokes decide on.  Pipelined mode reads the
        PREVIOUS iteration's packed scalars right after dispatching the
        next step, so the host never blocks on the in-flight program —
        the hub's decisions lag one iteration (bounds are valid at every
        iterate, so a one-iteration-late termination is still certified;
        this is exactly the reference's stale-window tolerance,
        ref:cylinders/hub.py write-id freshness).  The candidate tensors
        ride the same pipeline so a cached value is always paired with
        the candidate it was evaluated at."""
        inflight = (self.wstate.scalars, self.wstate.xhat_cand,
                    self.wstate.slam_cand, self.wstate.shuf_cand)
        if pipelined and self._scalars_inflight is not None:
            scalars, xc, sc_, fc = self._scalars_inflight
        else:
            scalars, xc, sc_, fc = inflight
        self._scalars_inflight = inflight
        vals = np.asarray(scalars)
        self.scalar_cache = dict(zip(SCALAR_KEYS, (float(v) for v in vals)))
        # device refs, transferred only when a spoke actually offers
        self.cand_cache = {"xhat": xc, "slam": sc_, "shuf": fc}

    def flush_scalars(self):
        """Synchronize the cache to the LATEST iterate (final harvest)."""
        if self.wstate is not None:
            self._cache_scalars()

    def _read_conv(self) -> float:
        return self.scalar_cache["conv"]

    def state_template(self):
        st, _, _ = jax.eval_shape(
            partial(fused_iter0, opts=ph_mod.kernel_opts(self.options),
                    wopts=self.wheel_options),
            self.batch, self.rho)
        return st

    def _iter0_impl(self):
        self.wstate, tb, cert = fused_iter0(
            self.batch, self.rho, ph_mod.kernel_opts(self.options),
            self.wheel_options)
        self._cache_scalars()
        return self.wstate.ph, tb, cert

    def _iterk_impl(self):
        sid = jnp.asarray(
            int(self._shuf_order[self._shuf_cursor]), jnp.int32)
        self._shuf_cursor = (self._shuf_cursor + 1) % len(self._shuf_order)
        wopts = self.wheel_options
        p = max(1, int(wopts.spoke_period))
        if p > 1 and (self._iter % p) != 0:
            # hub-only variant: spoke planes skipped, their state/bounds
            # carried untouched (harvests re-read last values — folding
            # is idempotent)
            wopts = dataclasses.replace(
                wopts, lag_windows=0, xhat_windows=0, slam_windows=0,
                shuffle_windows=0)
        # self.state may have been rebound by extensions/convergers
        # (e.g. rho updaters) — fold it back into the wheel state first
        self.wstate = fused_iterk(
            self.batch,
            dataclasses.replace(self.wstate, ph=self.state),
            ph_mod.kernel_opts(self.options), wopts, sid)
        self._cache_scalars(pipelined=True)
        return self.wstate.ph
