###############################################################################
# Schur-complement interior point, TPU-native.
#
# The reference delegates to parapint's MPI Schur-complement IP solver
# with HSL MA27 factorizations per scenario (ref:mpisppy/opt/sc.py:32-114)
# — continuous two-stage problems only.  This module implements the
# same decomposition natively:
#
#   min sum_s p_s (c_s'v_s + 1/2 v_s'Q_s v_s)
#   s.t. per scenario:  A_s v_s in [bl, bu]  (slacks t on ineq rows),
#                       box on v_s,   E v_s - x = 0  (consensus rows)
#
# One Mehrotra predictor-corrector iteration =
#   * diagonal D_s = Q + barrier terms (q is diagonal, so D is too — no
#     per-scenario sparse factorization needed);
#   * per-scenario NORMAL matrices  M_s = G_s D_s^-1 G_s'  and their
#     batched Cholesky factorizations — the MXU-heavy op, vmapped over
#     the scenario axis (sharded: each device factors its scenarios);
#   * the N x N SCHUR complement on the consensus block
#     K = sum_s (M_s^-1 J)[cons rows], reduced across scenarios (a psum
#     under sharding — the analog of parapint's MPI reduction), then
#     one small dense solve for dx and batched back-substitution.
#
# Precision: interior-point factorizations need f64 (the reference's
# MA27 is f64 for the same reason; pure-f32 Newton systems follow
# spurious near-complementary paths — measured, not hypothetical).  The
# batched loop runs under x64, preferring the CPU backend while TPU f64
# linear algebra is unsupported; the decomposition structure (vmapped
# factorizations + scenario-axis reduction) is the TPU design and moves
# on-chip unchanged when f64 lands.  Box/row/objective normalization +
# dtype-aware jitter with refinement against the TRUE normal matrix +
# best-iterate tracking give ~1e-9 scaled residuals.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu import global_toc
from mpisppy_tpu.core.batch import ScenarioBatch

Array = jax.Array
HI = jax.lax.Precision.HIGHEST


@dataclasses.dataclass(frozen=True)
class SCOptions:
    max_iter: int = 60
    tol: float = 1e-8          # mu target
    frac_to_bound: float = 0.995
    display_progress: bool = False


def _structure(batch: ScenarioBatch):
    """Static problem structure (host side): dense G blocks, slack
    layout, finite-bound masks.  Requires a shared equality-row pattern
    across scenarios and zero integer slots."""
    qp = batch.qp
    S = batch.num_scenarios
    n = qp.n
    m = qp.m
    bl = np.broadcast_to(np.asarray(qp.bl, np.float64), (S, m))
    bu = np.broadcast_to(np.asarray(qp.bu, np.float64), (S, m))
    eq = np.isclose(bl, bu)
    if not (eq == eq[0:1]).all():
        raise ValueError("SchurComplement needs a shared equality-row "
                         "pattern across scenarios")
    eq = eq[0]
    if bool(np.asarray(batch.integer_slot).any()):
        raise ValueError("SchurComplement supports continuous problems "
                         "only (ref:mpisppy/opt/sc.py docstring)")
    if batch.tree.num_nodes != 1:
        raise ValueError("SchurComplement is two-stage only")
    ineq = ~eq
    m_in = int(ineq.sum())
    N = batch.num_nonants

    if hasattr(qp.A, "vals"):  # EllMatrix -> dense (SC is dense anyway)
        vals = np.asarray(qp.A.vals, np.float64)
        cols = np.asarray(qp.A.cols)
        if vals.ndim == 2:
            dense = np.zeros((m, n))
            np.add.at(dense, (np.repeat(np.arange(m), cols.shape[1]),
                              cols.reshape(-1)), vals.reshape(-1))
            A = np.broadcast_to(dense, (S, m, n))
        else:
            dense = np.zeros((S, m, n))
            for s in range(S):
                np.add.at(dense[s],
                          (np.repeat(np.arange(m), cols.shape[1]),
                           cols.reshape(-1)), vals[s].reshape(-1))
            A = dense
    else:
        A = np.broadcast_to(np.asarray(qp.A, np.float64), (S, m, n))

    # variable vector per scenario: w = [v (n); t (m_in)]
    nw = n + m_in
    # constraint rows per scenario: m (A rows) + N (consensus)
    G = np.zeros((S, m + N, nw))
    G[:, :m, :n] = A
    G[:, np.nonzero(ineq)[0], n + np.arange(m_in)] = -1.0
    nonant_idx = np.asarray(batch.nonant_idx)
    # consensus must tie ORIGINAL-space nonants: the batch's Ruiz
    # scalings are per-scenario, so the row coefficient is d_non[s, j]
    # (x then lives in original units for every scenario)
    d_non = np.broadcast_to(np.asarray(batch.d_non, np.float64), (S, N))
    for j in range(N):
        G[:, m + j, nonant_idx[j]] = d_non[:, j]

    # rhs: eq rows -> bl; ineq rows -> 0; consensus rows -> 0 (x enters
    # through the J coupling)
    b = np.zeros((S, m + N))
    b[:, np.nonzero(eq)[0]] = bl[:, eq]

    # boxes on w
    l_v = np.broadcast_to(np.asarray(qp.l, np.float64), (S, n))
    u_v = np.broadcast_to(np.asarray(qp.u, np.float64), (S, n))
    lw = np.concatenate([l_v, bl[:, ineq]], axis=1)
    uw = np.concatenate([u_v, bu[:, ineq]], axis=1)

    c = np.broadcast_to(np.asarray(qp.c, np.float64), (S, n))
    q = np.broadcast_to(np.asarray(qp.q, np.float64), (S, n))
    cw = np.concatenate([c, np.zeros((S, m_in))], axis=1)
    qw = np.concatenate([q, np.zeros((S, m_in))], axis=1)

    # IPM-side normalization (beyond the batch's Ruiz, which targets A
    # only): bring every BOX to O(1) with a per-column scale and every
    # G row to unit norm — the barrier geometry and the normal matrices
    # are then well conditioned in f32.  The consensus x lives in the
    # column-scaled space of the NONANT columns (col_s must be shared
    # across scenarios there so x is well defined).
    finite_mag = np.maximum(np.where(np.isfinite(lw), np.abs(lw), 0.0),
                            np.where(np.isfinite(uw), np.abs(uw), 0.0))
    col_s = np.maximum(1.0, finite_mag)            # (S, nw)
    col_s[:, nonant_idx] = col_s[:, nonant_idx].max(axis=0)[None, :]
    G = G * col_s[:, None, :]
    lw = lw / col_s
    uw = uw / col_s
    cw = cw * col_s
    qw = qw * col_s * col_s
    # objective normalization: a positive constant doesn't move the
    # argmin, and it keeps the barrier duals O(1) (costs after column
    # scaling can reach 1e10 otherwise)
    obj_scale = max(1.0, float(np.abs(cw).max()))
    cw = cw / obj_scale
    qw = qw / obj_scale
    row_s = np.maximum(np.linalg.norm(G, axis=2), 1e-8)  # (S, m+N)
    # consensus rows keep a SHARED row scale so the x column is uniform
    row_s[:, m:] = row_s[:, m:].max(axis=0)[None, :]
    G = G / row_s[:, :, None]
    b = b / row_s
    # after row scaling, consensus row j reads
    # (d_non col_s / row_s) v - x / row_s = 0 with J = -I: the solved x
    # is original-space UP TO the shared row scale, recovered in solve()
    return dict(G=G, b=b, lw=lw, uw=uw, cw=cw, qw=qw, n=n, m=m,
                m_in=m_in, N=N, col_s=col_s,
                x_row_scale=row_s[0, m:])


@partial(jax.jit, static_argnames=("N", "opts"))
def _sc_solve(G: Array, b: Array, lw: Array, uw: Array, cw: Array,
              qw: Array, p: Array, N: int, opts: SCOptions):
    """Batched Mehrotra predictor-corrector.  Shapes: G (S, mc, nw),
    b (S, mc), boxes/costs (S, nw), p (S,).  The LAST N rows of G are
    the consensus rows; their x coupling is J = -I."""
    S, mc, nw = G.shape
    dt = G.dtype
    has_l = jnp.isfinite(lw)
    has_u = jnp.isfinite(uw)
    l_safe = jnp.where(has_l, lw, 0.0)
    u_safe = jnp.where(has_u, uw, 0.0)
    n_act = jnp.maximum(has_l.sum() + has_u.sum(), 1).astype(dt)

    # objective scaled by p so the consensus duals balance globally
    cw = p[:, None] * cw
    qw = p[:, None] * qw

    # interior start: midpoint of finite boxes, 1.0 margin one-sided
    mid = jnp.where(has_l & has_u, 0.5 * (l_safe + u_safe),
                    jnp.where(has_l, l_safe + 1.0,
                              jnp.where(has_u, u_safe - 1.0, 0.0)))
    w0 = mid
    # duals start at the COST scale (Mehrotra-style): z = 1 with costs
    # of 1e5 stalls the first dozen iterations
    z0 = 1.0 + jnp.abs(cw)
    zl0 = jnp.where(has_l, z0, 0.0)
    zu0 = jnp.where(has_u, z0, 0.0)
    y0 = jnp.zeros((S, mc), dt)
    x0 = jnp.zeros((N,), dt)

    # shared unit-rhs block for the J columns: E (mc, N)
    EJ = jnp.zeros((mc, N), dt).at[mc - N:, :].set(-jnp.eye(N, dtype=dt))

    def mu_of(w, zl, zu):
        gaps = (jnp.where(has_l, (w - l_safe) * zl, 0.0)
                + jnp.where(has_u, (u_safe - w) * zu, 0.0))
        return jnp.sum(gaps) / n_act

    def residuals(w, y, zl, zu, x):
        rp = jnp.einsum("smw,sw->sm", G, w, precision=HI) - b
        rp = rp.at[:, mc - N:].add(-x[None, :])
        rd = (cw + qw * w
              - jnp.einsum("smw,sm->sw", G, y, precision=HI)
              - zl + zu)
        rx = jnp.sum(y[:, mc - N:], axis=0)
        return rp, rd, rx

    def step(carry, _):
        w, y, zl, zu, x, done, best = carry
        rp, rd, rx = residuals(w, y, zl, zu, x)
        mu = mu_of(w, zl, zu)

        floor = jnp.asarray(jnp.finfo(dt).eps, dt) ** 0.9
        dl = jnp.where(has_l, jnp.maximum(w - l_safe, floor), 1.0)
        du = jnp.where(has_u, jnp.maximum(u_safe - w, floor), 1.0)
        D = qw + jnp.where(has_l, zl / dl, 0.0) \
            + jnp.where(has_u, zu / du, 0.0) + jnp.finfo(dt).tiny ** 0.5
        Dinv = 1.0 / D

        GD = G * Dinv[:, None, :]
        M = jnp.einsum("smw,skw->smk", GD, G, precision=HI)
        # dtype-aware relative jitter keeps the Cholesky stable as the
        # barrier spreads the diagonal; refinement below corrects
        # against the TRUE M so the jitter bias does not persist
        jit_rel = 50.0 * jnp.finfo(dt).eps
        diag_scale = jnp.maximum(
            jnp.max(jnp.abs(jnp.diagonal(M, axis1=1, axis2=2)),
                    axis=-1, keepdims=True), 1e-12)[..., None]
        M_reg = M + jit_rel * diag_scale * jnp.eye(mc, dtype=dt)[None]
        L = jnp.linalg.cholesky(M_reg)

        def msolve(r):
            """Batched M^{-1} r (r: (S, mc) or (S, mc, k)) with one
            refinement step."""
            rr = r if r.ndim == 3 else r[..., None]

            def base(v):
                z = jax.scipy.linalg.solve_triangular(L, v, lower=True)
                return jax.scipy.linalg.solve_triangular(
                    L, z, lower=True, trans=1)

            u0 = base(rr)
            for _ in range(2):   # refine against the TRUE (unjittered) M
                resid = rr - jnp.einsum("smk,skj->smj", M, u0,
                                        precision=HI)
                u0 = u0 + base(resid)
            return u0 if r.ndim == 3 else u0[..., 0]

        # P = M^{-1} J  (S, mc, N); K_s = P[last N rows] (negative def.)
        P = msolve(jnp.broadcast_to(EJ[None], (S, mc, N)))
        K = jnp.sum(P[:, mc - N:, :], axis=0)      # psum under sharding
        K = K - 1e-9 * jnp.eye(N, dtype=dt)        # keep strictly nd

        def kkt_solve(rl, ru, rp_eff, rx_eff):
            """One Newton solve given complementarity targets rl/ru
            (zero components where the bound is infinite)."""
            rd_hat = rd - jnp.where(has_l, rl / dl, 0.0) \
                + jnp.where(has_u, ru / du, 0.0)
            # M dy + J dx = -rp + G D^-1 rd_hat =: g
            g = -rp_eff + jnp.einsum("smw,sw->sm", GD, rd_hat,
                                     precision=HI)
            Mg = msolve(g)
            # sum_s dy[cons] = -rx  =>  K dx = rx + sum_s Mg[cons]
            rhs = rx_eff + jnp.sum(Mg[:, mc - N:], axis=0)
            dx = jnp.linalg.solve(K, rhs)
            dy = Mg - jnp.einsum("smn,n->sm", P, dx, precision=HI)
            dw = Dinv * (jnp.einsum("smw,sm->sw", G, dy, precision=HI)
                         - rd_hat)
            dzl = jnp.where(has_l, (rl - zl * dw) / dl, 0.0)
            dzu = jnp.where(has_u, (ru + zu * dw) / du, 0.0)
            return dw, dy, dx, dzl, dzu

        def max_step(v, dv, mask):
            r = jnp.where(mask & (dv < 0.0),
                          -v / jnp.minimum(dv, -1e-30), jnp.inf)
            return jnp.minimum(1.0, opts.frac_to_bound * jnp.min(r))

        # ---- affine (predictor): complementarity target 0
        rl_a = jnp.where(has_l, -dl * zl, 0.0)
        ru_a = jnp.where(has_u, -du * zu, 0.0)
        dw_a, dy_a, dx_a, dzl_a, dzu_a = kkt_solve(rl_a, ru_a, rp, rx)
        a_p = jnp.minimum(max_step(dl, dw_a, has_l),
                          max_step(du, -dw_a, has_u))
        a_d = jnp.minimum(max_step(zl, dzl_a, has_l),
                          max_step(zu, dzu_a, has_u))
        mu_aff = mu_of(w + a_p * dw_a, zl + a_d * dzl_a,
                       zu + a_d * dzu_a)
        sigma = jnp.clip((mu_aff / jnp.maximum(mu, 1e-30)) ** 3,
                         0.0, 1.0)

        # ---- corrector (centering + Mehrotra second-order terms)
        rl = jnp.where(has_l, sigma * mu - dl * zl - dw_a * dzl_a, 0.0)
        ru = jnp.where(has_u, sigma * mu - du * zu + dw_a * dzu_a, 0.0)
        dw, dy, dx, dzl, dzu = kkt_solve(rl, ru, rp, rx)
        a_p = jnp.minimum(max_step(dl, dw, has_l),
                          max_step(du, -dw, has_u))
        a_d = jnp.minimum(max_step(zl, dzl, has_l),
                          max_step(zu, dzu, has_u))

        w1 = w + a_p * dw
        # f32 rounding can land a hair outside the box despite the
        # fraction-to-boundary rule; negative gaps corrupt mu and the
        # barrier, so clip strictly inside
        w1 = jnp.where(has_l, jnp.maximum(w1, l_safe + floor), w1)
        w1 = jnp.where(has_u, jnp.minimum(w1, u_safe - floor), w1)
        x1 = x + a_p * dx
        y1 = y + a_d * dy
        zl1 = jnp.where(has_l, jnp.maximum(zl + a_d * dzl, 1e-12), 0.0)
        zu1 = jnp.where(has_u, jnp.maximum(zu + a_d * dzu, 1e-12), 0.0)

        mu1 = mu_of(w1, zl1, zu1)
        rp1, rd1, rx1 = residuals(w1, y1, zl1, zu1, x1)
        scale_p = 1.0 + jnp.max(jnp.abs(b))
        scale_d = 1.0 + jnp.max(jnp.abs(cw))
        resid = jnp.maximum(jnp.max(jnp.abs(rp1)) / scale_p,
                            jnp.max(jnp.abs(rd1)) / scale_d)
        resid = jnp.maximum(resid, jnp.max(jnp.abs(rx1)) / scale_d)
        done1 = (mu1 <= opts.tol) & (resid <= 100.0 * opts.tol)
        # past the precision floor a step degrades (or NaNs): never let
        # the tracked iterate get worse — keep the BEST (mu + resid)
        # point seen, which is what gets returned
        finite = (jnp.isfinite(w1).all() & jnp.isfinite(y1).all()
                  & jnp.isfinite(x1).all() & jnp.isfinite(zl1).all()
                  & jnp.isfinite(zu1).all() & jnp.isfinite(mu1))
        keep = done | ~finite
        w1 = jnp.where(keep, w, w1)
        y1 = jnp.where(keep, y, y1)
        zl1 = jnp.where(keep, zl, zl1)
        zu1 = jnp.where(keep, zu, zu1)
        x1 = jnp.where(keep, x, x1)
        mu1 = jnp.where(keep, mu, mu1)
        resid1 = jnp.where(finite, resid, jnp.inf)
        score1 = jnp.where(finite, mu1 + resid1, jnp.inf)

        bw, by, bzl, bzu, bx, bscore, bmu, bresid = best
        better = score1 < bscore
        best1 = (jnp.where(better, w1, bw), jnp.where(better, y1, by),
                 jnp.where(better, zl1, bzl),
                 jnp.where(better, zu1, bzu),
                 jnp.where(better, x1, bx),
                 jnp.where(better, score1, bscore),
                 jnp.where(better, mu1, bmu),
                 jnp.where(better, resid1, bresid))
        out = (w1, y1, zl1, zu1, x1, done | done1 | ~finite, best1)
        return out, (mu1, resid1)

    inf0 = jnp.asarray(jnp.inf, dt)
    best0 = (w0, y0, zl0, zu0, x0, inf0, inf0, inf0)
    carry = (w0, y0, zl0, zu0, x0, jnp.zeros((), bool), best0)
    carry, trace = jax.lax.scan(step, carry, None,
                                length=opts.max_iter)
    _, _, _, _, _, done, best = carry
    bw, by, bzl, bzu, bx, bscore, bmu, bresid = best
    return bw, bx, done | (bscore <= 101.0 * opts.tol), bmu, bresid


class SchurComplement:
    """ref:mpisppy/opt/sc.py:67 — two-stage continuous solves only."""

    def __init__(self, options, batch: ScenarioBatch,
                 scenario_names=None):
        if isinstance(options, dict):
            options = SCOptions(**options)
        self.options = options
        self.batch = batch
        self.scenario_names = scenario_names
        self._s = _structure(batch)

    def solve(self) -> dict:
        import time
        s = self._s
        batch = self.batch
        p = np.asarray(batch.p, np.float64)
        # EXPLICIT CPU-offload boundary (round-2 review, weak #4):
        # interior-point path-following needs f64 factorizations (the
        # reference's MA27 is f64 for the same reason; pure-f32 Newton
        # systems follow spurious near-complementary paths).  Current
        # TPUs do not compile f64 linear algebra, so when the default
        # backend is an accelerator the batched loop runs x64 ON THE
        # HOST CPU — announced, recorded in the result
        # ('backend_used', 'solve_seconds'), and asserted by
        # tests/test_sc.py.  The decomposition structure (vmapped
        # factorizations + scenario-axis reduction) is the TPU design
        # and moves on-chip unchanged when f64 lands.
        dev = None
        try:
            if jax.default_backend() != "cpu":
                dev = jax.devices("cpu")[0]
                global_toc(
                    "SC: f64 interior point offloaded to host CPU "
                    f"(default backend {jax.default_backend()} has no "
                    "f64 linear algebra)", True)
        except RuntimeError:
            dev = None
        backend_used = "cpu" if dev is not None else jax.default_backend()
        import contextlib
        ctx = jax.default_device(dev) if dev is not None \
            else contextlib.nullcontext()
        dt = jnp.float64
        t0 = time.perf_counter()
        # jax.enable_x64 left the top-level namespace in current JAX;
        # the context manager lives in jax.experimental now
        from jax.experimental import enable_x64 as _enable_x64
        with _enable_x64(), ctx:
            w, x, done, mu, resid = _sc_solve(
                jnp.asarray(s["G"], dt), jnp.asarray(s["b"], dt),
                jnp.asarray(s["lw"], dt), jnp.asarray(s["uw"], dt),
                jnp.asarray(s["cw"], dt), jnp.asarray(s["qw"], dt),
                jnp.asarray(p, dt), s["N"], self.options)
        solve_seconds = time.perf_counter() - t0
        # undo the IPM column scaling -> batch (Ruiz) space
        v = np.asarray(w[:, :s["n"]], np.float64) \
            * s["col_s"][:, :s["n"]]
        d_col = np.broadcast_to(np.asarray(batch.d_col),
                                (batch.num_scenarios, s["n"]))
        v_orig = v * d_col
        c = np.broadcast_to(np.asarray(batch.qp.c, np.float64), v.shape)
        q = np.broadcast_to(np.asarray(batch.qp.q, np.float64), v.shape)
        per_scen = (c * v + 0.5 * q * v * v).sum(axis=1)
        obj = float((p * per_scen).sum())
        # x lives in (row-scaled) ORIGINAL units: the consensus rows
        # carry the d_non map already
        x_orig = np.asarray(x, np.float64) * s["x_row_scale"]
        if self.options.display_progress:
            global_toc(f"SC: mu={float(mu):.3e} resid={float(resid):.3e}"
                       f" done={bool(done)} obj={obj:.6g}", True)
        return {"objective": obj, "x": x_orig, "v": v_orig,
                "converged": bool(done), "mu": float(mu),
                "resid": float(resid), "backend_used": backend_used,
                "solve_seconds": round(solve_seconds, 4)}
