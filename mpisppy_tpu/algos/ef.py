###############################################################################
# Extensive form: all scenarios as ONE BoxQP.
#
# The reference builds the EF as a Pyomo model with per-scenario blocks,
# a probability-weighted objective, and reference-variable
# nonanticipativity equality constraints
# (ref:mpisppy/utils/sputils.py:143-357), then hands it to a MIP solver
# (ref:mpisppy/opt/ef.py:75-104).  Here the EF is assembled as one
# block-diagonal BoxQP — scenario blocks on the diagonal, nonant
# equality rows x_{s,i} == x_{ref(s),i} linking them — and solved by the
# same batched PDHG kernel (a single "scenario" of size S*n).  It is the
# correctness oracle for the decomposition algorithms: PH's converged
# objective must match the EF objective.
#
# Assembly is SPARSE by default beyond toy scale: the block-diagonal +
# two-nonzero link-row structure is exactly ELL-friendly (every scenario
# row keeps its within-block width; link rows have width 2), so the EF
# A is an ops.sparse.EllMatrix and HBM holds O(nnz), not O(m * S * n).
# The reference gets the same effect through Pyomo->Gurobi sparse
# ingestion (ref:mpisppy/utils/sputils.py:143-357); a dense (m, S*n)
# assembly caps the oracle at ~10 scenarios (round-2 review, weak #2).
###############################################################################
from __future__ import annotations

import dataclasses

import numpy as np
import jax.numpy as jnp

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.core.tree import ScenarioTree, two_stage_tree
from mpisppy_tpu.ops import boxqp, pdhg


@dataclasses.dataclass(frozen=True)
class EFProblem:
    """The assembled extensive form plus bookkeeping to read solutions."""

    qp: boxqp.BoxQP           # scaled
    scaling: boxqp.Scaling
    n_per_scen: int
    probs: np.ndarray         # (S,)
    nonant_idx: np.ndarray    # (N,) columns within one scenario block
    tree: ScenarioTree


def build_ef(specs: list[ScenarioSpec],
             tree: ScenarioTree | None = None,
             dtype=jnp.float32,
             scale: bool = True,
             sparse: bool | None = None) -> EFProblem:
    """Assemble the extensive form.  `sparse=None` auto-selects: ELL
    whenever any scenario matrix is scipy-sparse or the dense (m, S*n)
    block would exceed ~2e7 entries; tiny oracles stay dense."""
    S = len(specs)
    n = specs[0].c.shape[0]
    nonant_idx = np.asarray(specs[0].nonant_idx, np.int64)
    N = len(nonant_idx)
    if tree is None:
        tree = two_stage_tree(S, N)

    probs = np.array([1.0 / S if sp.probability is None else sp.probability
                      for sp in specs])

    # Objective: sum_s p_s f_s  (block-concatenated variables).
    c = np.concatenate([probs[s] * np.asarray(specs[s].c, np.float64)
                        for s in range(S)])
    q = np.concatenate([
        probs[s] * (np.zeros(n) if specs[s].q is None
                    else np.asarray(specs[s].q, np.float64))
        for s in range(S)])
    l = np.concatenate([np.asarray(sp.l, np.float64) for sp in specs])
    u = np.concatenate([np.asarray(sp.u, np.float64) for sp in specs])

    # Nonanticipativity: within each tree node, every member scenario's
    # slot equals the first member's (reference-variable convention,
    # ref:mpisppy/utils/sputils.py:300-357).
    node_of_slot = tree.node_of_slot()  # (S, N)
    link_rows = []
    for node in range(tree.num_nodes):
        for i in range(N):
            members = np.nonzero(node_of_slot[:, i] == node)[0]
            for s in members[1:]:
                link_rows.append((members[0], s, i))

    m_block = sum(sp.A.shape[0] for sp in specs)
    m = m_block + len(link_rows)
    bl = np.empty(m)
    bu = np.empty(m)

    import scipy.sparse as sps
    any_sparse = any(sps.issparse(sp.A) for sp in specs)
    if sparse is None:
        sparse = any_sparse or m * S * n > 2e7

    if sparse:
        blocks = [sps.csr_matrix(np.asarray(sp.A) if not sps.issparse(sp.A)
                                 else sp.A) for sp in specs]
        parts = [sps.block_diag(blocks, format="csr")]
        if link_rows:
            rows = np.repeat(np.arange(len(link_rows)), 2)
            cols = np.empty(2 * len(link_rows), np.int64)
            data = np.tile([1.0, -1.0], len(link_rows))
            for r_, (s0, s, i) in enumerate(link_rows):
                cols[2 * r_] = s0 * n + nonant_idx[i]
                cols[2 * r_ + 1] = s * n + nonant_idx[i]
            parts.append(sps.csr_matrix((data, (rows, cols)),
                                        shape=(len(link_rows), S * n)))
        from mpisppy_tpu.ops import sparse as sparse_mod
        A = sparse_mod.ell_from_scipy(sps.vstack(parts).tocsr(), dtype)
    else:
        A = np.zeros((m, S * n))
    r = 0
    for s, sp in enumerate(specs):
        ms = sp.A.shape[0]
        if not sparse:
            As = sp.A.toarray() if hasattr(sp.A, "toarray") else sp.A
            A[r:r + ms, s * n:(s + 1) * n] = As
        bl[r:r + ms] = sp.bl
        bu[r:r + ms] = sp.bu
        r += ms
    for (s0, s, i) in link_rows:
        if not sparse:
            A[r, s0 * n + nonant_idx[i]] = 1.0
            A[r, s * n + nonant_idx[i]] = -1.0
        bl[r] = bu[r] = 0.0
        r += 1

    # SOC metadata rides through assembly: per-scenario blocks shift by
    # their block-diagonal row offset (link rows stay box rows), so the
    # EF solve runs the same conic kernel as the decomposed batch
    cones = None
    if any(sp.soc_blocks for sp in specs):
        from mpisppy_tpu.ops import cones as cones_mod
        all_blocks = []
        off = 0
        for sp in specs:
            for blk in (sp.soc_blocks or []):
                all_blocks.append(np.asarray(blk, np.int64) + off)
            off += sp.A.shape[0]
        cones = cones_mod.cone_spec(m, all_blocks)
        cones_mod.validate_against_bounds(cones, bl, bu)
    if sparse:
        qp = boxqp.BoxQP(
            c=jnp.asarray(c, dtype), q=jnp.asarray(q, dtype), A=A,
            bl=jnp.asarray(bl, dtype), bu=jnp.asarray(bu, dtype),
            l=jnp.asarray(l, dtype), u=jnp.asarray(u, dtype),
            cones=cones)
    else:
        qp = boxqp.make_boxqp(c, A, bl, bu, l, u, q=q, dtype=dtype,
                              cones=cones)
    if scale:
        qp, scaling = boxqp.ruiz_scale(qp)
    else:
        scaling = boxqp.Scaling(d_row=np.ones(m), d_col=np.ones(S * n))
    return EFProblem(qp=qp, scaling=scaling, n_per_scen=n, probs=probs,
                     nonant_idx=nonant_idx, tree=tree)


def root_fix_columns(efp: EFProblem):
    """(flat_cols, d_flat): the EF-wide flat column indices of every
    scenario block's ROOT-stage nonant slots, and their column scaling.
    The single source of truth for 'fix the root nonants at x̂' —
    shared by ExtensiveForm.fix_root_nonants and the EFXhatInnerBound
    spoke so the column/scaling convention cannot drift."""
    root_slots = np.nonzero(efp.tree.slot_stage == 1)[0]
    cols_one = np.asarray(efp.nonant_idx)[root_slots]
    S = len(efp.probs)
    n = efp.n_per_scen
    flat = (np.arange(S)[:, None] * n + cols_one[None, :]).ravel()
    d_flat = np.asarray(efp.scaling.d_col)[flat]
    return root_slots, flat, d_flat


class ExtensiveForm:
    """Direct EF solve — API parity with ref:mpisppy/opt/ef.py:16-155.

    options: dict with optional 'tol', 'max_iters'.
    """

    def __init__(self, options, all_scenario_names, scenario_creator,
                 scenario_creator_kwargs=None, tree=None, dtype=jnp.float32):
        kwargs = scenario_creator_kwargs or {}
        self.all_scenario_names = list(all_scenario_names)
        self.specs = [scenario_creator(name, **kwargs)
                      for name in self.all_scenario_names]
        self.options = dict(options or {})
        self.ef = build_ef(self.specs, tree=tree, dtype=dtype)
        self._state = None

    def solve_extensive_form(self) -> pdhg.PDHGState:
        opts = pdhg.PDHGOptions(
            tol=self.options.get("tol", 1e-6),
            max_iters=self.options.get("max_iters", 100_000),
        )
        self._state = pdhg.solve(self.ef.qp, opts)
        return self._state

    @property
    def x(self) -> np.ndarray:
        """(S, n) per-scenario solution in original space."""
        xs = np.asarray(self._state.x) * self.ef.scaling.d_col
        return xs.reshape(len(self.specs), self.ef.n_per_scen)

    def fix_root_nonants(self, xhat_root: np.ndarray):
        """Collapse the ROOT-stage nonant boxes at xhat in every
        scenario block (the EF analog of _fix_root_nonants,
        ref:mpisppy/spopt.py:686-725).  Call before
        solve_extensive_form."""
        import dataclasses as _dc
        root_slots, flat, d_flat = root_fix_columns(self.ef)
        xhat_root = np.asarray(xhat_root, np.float64)
        if xhat_root.shape[-1] != len(root_slots):
            raise ValueError(
                f"xhat has {xhat_root.shape[-1]} values; the root "
                f"stage has {len(root_slots)} nonant slots")
        S = len(self.specs)
        l = np.array(np.asarray(self.ef.qp.l), np.float64)
        u = np.array(np.asarray(self.ef.qp.u), np.float64)
        xs = np.tile(xhat_root, S) / d_flat
        l[flat] = xs
        u[flat] = xs
        self.ef = _dc.replace(
            self.ef, qp=_dc.replace(
                self.ef.qp,
                l=jnp.asarray(l, self.ef.qp.l.dtype),
                u=jnp.asarray(u, self.ef.qp.u.dtype)))

    def get_objective_value(self) -> float:
        """EF objective in original space (ref:opt/ef.py:106)."""
        x = self.x
        val = 0.0
        for s, sp in enumerate(self.specs):
            qs = np.zeros_like(sp.c) if sp.q is None else sp.q
            val += self.ef.probs[s] * float(
                sp.c @ x[s] + 0.5 * x[s] @ (qs * x[s]))
        return val

    def get_root_solution(self) -> dict[str, float]:
        """First-stage (ROOT) variable values (ref:opt/ef.py:121-135)."""
        x = self.x
        root_slots = np.nonzero(self.ef.tree.slot_stage == 1)[0]
        return {f"x{self.ef.nonant_idx[i]}": float(x[0, self.ef.nonant_idx[i]])
                for i in root_slots}

    def nonants(self):
        """Iterate (scenario_name, slot, value) (ref:opt/ef.py:138-147)."""
        x = self.x
        for s, name in enumerate(self.all_scenario_names):
            for i, col in enumerate(self.ef.nonant_idx):
                yield name, i, float(x[s, col])
