###############################################################################
# L-shaped (Benders) decomposition, TPU-native.
#
# The reference (ref:mpisppy/opt/lshaped.py:29-783) builds a Pyomo root
# problem plus per-scenario subproblems and iterates master solve +
# sequential per-rank cut generation through Pyomo's Benders generator
# (ref:mpisppy/utils/lshaped_cuts.py:34, dual sign conventions at
# :19-32).  Two-stage, min problems only — same restriction here.
#
# TPU-native design:
#   * ALL scenario subproblems (first stage fixed at the master's x̂) are
#     ONE batched PDHG solve — cut generation is a single tensor program,
#     not a loop over CPU solver calls.
#   * Optimality cuts come from the DUAL side: for any dual iterate
#     (x, y) of the fixed-nonant subproblem, the Fenchel bound
#     D(x, y; x̂') is affine in x̂' with slope = the nonant reduced cost,
#     so  phi_s(x̂') >= alpha_s + g_s·x̂'  is valid even for INEXACT
#     subproblem solves (the reference needs exact LP duals from Gurobi;
#     a first-order kernel gets validity for free from weak duality).
#   * Feasibility cuts come from the kernel's Farkas certificates
#     (ops/boxqp.infeasibility_certificate): the certificate value is
#     affine in x̂ through the collapsed nonant box, giving the exact
#     analog of the reference's feasibility cuts.
#   * The master is a small BoxQP over [x_nonant; eta] with a
#     fixed-capacity cut buffer (static shapes => one compiled master
#     solve reused every iteration).  Single-cut (aggregated, classic
#     L-shaped) or multi-cut (per-scenario eta_s, faster on few
#     scenarios) — ref's root_solver options analog.
#
# Requires zero quadratic cost on the first-stage (nonant) columns: the
# dual bound is then exactly affine in x̂.  (The reference's L-shaped is
# LP-only, so this is a strict superset: second-stage diagonal quadratics
# are fine.)
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu import global_toc
from mpisppy_tpu.core.batch import ScenarioBatch, concretize
from mpisppy_tpu.ops import boxqp, pdhg
from mpisppy_tpu.ops.boxqp import BoxQP

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class LShapedOptions:
    """Static options (ref:mpisppy/opt/lshaped.py options dict:
    max_iter, tol, root_solver, valid_eta_lb)."""

    max_iter: int = 50
    tol: float = 1e-4              # relative ub-lb gap
    multicut: bool = False         # per-scenario eta (ref multi-cut mode)
    max_cuts: int = 256            # master cut-buffer capacity (rows)
    eta_lb: float | None = None    # valid lower bound on E[cost]; default:
    #                                wait-and-see dual bound - margin
    sub_pdhg: pdhg.PDHGOptions = pdhg.PDHGOptions(
        tol=1e-7, max_iters=100_000, detect_infeas=True)
    master_pdhg: pdhg.PDHGOptions = pdhg.PDHGOptions(
        tol=1e-7, max_iters=200_000)
    feas_tol: float = 1e-4         # primal-residual gate for ub validity
    display_progress: bool = False


@partial(jax.jit, static_argnames=("opts",))
def _subproblem_cuts(batch: ScenarioBatch, xhat: Array,
                     opts: pdhg.PDHGOptions):
    """Solve every scenario with nonants fixed at x̂ and extract, per
    scenario: the dual (outer) value, the optimality-cut slope, the
    primal objective + residual (inner-bound material), the status mask,
    and Farkas feasibility-cut pieces from two candidate rays.

    This one call replaces the reference's per-scenario subproblem loop
    + cut generator (ref:mpisppy/opt/lshaped.py:387-513)."""
    batch = concretize(batch)  # scengen: synthesize in-trace
    qp = batch.with_fixed_nonants(xhat)
    st = pdhg.solve(qp, opts, pdhg.init_state(qp, opts))

    # Optimality cut: D(x,y; x̂') = const + rc_non·(x̂'/d_non) for fixed
    # (x, y) — valid lower bound on phi_s(x̂') by weak duality (PDLP-form
    # dual, ops/boxqp.dual_objective).  g is the ORIGINAL-space slope.
    dual = boxqp.dual_objective(qp, st.x, st.y)
    rc = qp.c + qp.q * st.x + qp.rmatvec(st.y)
    g = rc[..., batch.nonant_idx] / batch.d_non          # (S, N)
    alpha = dual - jnp.sum(g * xhat, axis=-1)            # (S,)

    obj = boxqp.objective(qp, st.x)
    rp, rd, _ = boxqp.kkt_residuals(qp, st.x, st.y)

    def farkas_affine(y):
        """(qval, const, gf): certificate value at x̂, and its affine
        form qval(x̂') = const + gf·x̂' (must be <= 0 for feasibility)."""
        nrm = jnp.sum(jnp.abs(y), axis=-1, keepdims=True)
        yn = y / jnp.maximum(nrm, 1e-30)
        z = qp.rmatvec(yn)
        ztol = 32.0 * jnp.finfo(z.dtype).eps
        z = jnp.where(jnp.abs(z) <= ztol, 0.0, z)
        inf_j = jnp.where(z > 0.0, z * qp.l, z * qp.u)
        inf_j = jnp.where(z == 0.0, 0.0, inf_j)
        sup_i = jnp.where(yn > 0.0, yn * qp.bu, yn * qp.bl)
        sup_i = jnp.where(yn == 0.0, 0.0, sup_i)
        bad = (~jnp.isfinite(inf_j)).any(axis=-1) \
            | (~jnp.isfinite(sup_i)).any(axis=-1)
        qval = jnp.sum(inf_j, axis=-1) - jnp.sum(sup_i, axis=-1)
        gf = z[..., batch.nonant_idx] / batch.d_non
        const = qval - jnp.sum(gf * xhat, axis=-1)
        qval = jnp.where(bad, -jnp.inf, qval)
        return qval, const, gf

    # candidate rays: per-window displacement and the raw dual iterate
    # (mirrors ops/pdhg._restart's detection candidates)
    q1, c1, g1 = farkas_affine(st.y - st.y_anchor)
    q2, c2, g2 = farkas_affine(st.y)
    take2 = (q2 > q1)[..., None]
    feas_qval = jnp.maximum(q1, q2)
    feas_const = jnp.where(take2[..., 0], c2, c1)
    feas_g = jnp.where(take2, g2, g1)

    return dict(dual=dual, alpha=alpha, g=g, obj=obj, rp=rp, rd=rd,
                status=st.status, feas_qval=feas_qval,
                feas_const=feas_const, feas_g=feas_g)


@partial(jax.jit, static_argnames=("opts",))
def _master_solve(qp: BoxQP, opts: pdhg.PDHGOptions):
    """Solve the master and return (x, value, certified lower bound,
    dual residual, done)."""
    st = pdhg.solve(qp, opts, pdhg.init_state(qp, opts))
    val = boxqp.objective(qp, st.x)
    lb = boxqp.dual_objective(qp, st.x, st.y)
    _, rd, _ = boxqp.kkt_residuals(qp, st.x, st.y)
    return st.x, val, lb, rd, st.done


class LShapedMethod:
    """Host-side Benders driver (ref:mpisppy/opt/lshaped.py:29,515).

    Usage matches the reference shape:
        ls = LShapedMethod(options, batch)
        result = ls.lshaped_algorithm()
    """

    def __init__(self, options: LShapedOptions | dict,
                 batch: ScenarioBatch, scenario_names=None):
        if isinstance(options, dict):
            options = LShapedOptions(**options)
        self.options = options
        self.batch = batch
        self.scenario_names = scenario_names
        if batch.tree.num_nodes != 1:
            raise ValueError("LShaped is two-stage only "
                             "(ref:mpisppy/opt/lshaped.py:29 docstring)")
        qnon = np.asarray(batch.qp.q)[..., np.asarray(batch.nonant_idx)]
        if np.abs(qnon).max() > 0.0:
            raise ValueError("LShaped requires linear first-stage cost "
                             "(quadratic nonant cost breaks cut affinity)")
        self._setup_master_box()
        # results
        self.xhat: np.ndarray | None = None
        self.lb = -np.inf
        self.ub = np.inf
        self.iterations = 0
        self.trace: list[dict] = []
        self.spcomm = None  # cylinder seam (ref:lshaped.py spcomm hooks)

    # -- master construction ----------------------------------------------
    def _setup_master_box(self):
        """First-stage box in original space: the tightest intersection
        across scenarios (they coincide for well-posed models)."""
        b = self.batch
        n_idx = np.asarray(b.nonant_idx)
        S = b.num_scenarios
        l_s = np.broadcast_to(np.asarray(b.qp.l), (S, b.qp.n))[:, n_idx]
        u_s = np.broadcast_to(np.asarray(b.qp.u), (S, b.qp.n))[:, n_idx]
        d = np.broadcast_to(np.asarray(b.d_non), (S, len(n_idx)))
        self._x_l = np.max(l_s * d, axis=0)
        self._x_u = np.min(u_s * d, axis=0)
        self._N = len(n_idx)
        self._p = np.asarray(b.p, np.float64)

    def _master_qp(self, cuts_A, cuts_bl, cuts_bu,
                   eta_lb) -> "tuple[BoxQP, object]":
        """Master BoxQP over [x (N); eta (1 or S)] with the cut buffer.

        Scaled with Ruiz at every (re)build — cut coefficients mix cost
        magnitudes (1e2) with value magnitudes (1e5), which stalls an
        unscaled first-order solve."""
        N = self._N
        n_eta = self.batch.num_scenarios if self.options.multicut else 1
        n = N + n_eta
        c = np.zeros(n)
        if self.options.multicut:
            c[N:] = self._p
        else:
            c[N] = 1.0
        eta_lb = np.broadcast_to(np.asarray(eta_lb, np.float64), (n_eta,))
        l = np.concatenate([self._x_l, eta_lb])
        u = np.concatenate([self._x_u, np.full(n_eta, np.inf)])
        qp = boxqp.make_boxqp(c, cuts_A, cuts_bl, cuts_bu, l, u,
                              dtype=self.batch.qp.c.dtype)
        qp, scaling = boxqp.ruiz_scale(qp)
        return qp, scaling

    # -- the algorithm -----------------------------------------------------
    def lshaped_algorithm(self) -> dict:
        """ref:mpisppy/opt/lshaped.py:515 lshaped_algorithm()."""
        opts = self.options
        b = self.batch
        N = self._N
        n_eta = b.num_scenarios if opts.multicut else 1
        ncols = N + n_eta
        real = self._p > 0.0

        # Iter 0: unrestricted scenario solves give the wait-and-see
        # bound (default eta_lb) and the initial x̂ = E[x_non]
        # (ref:lshaped.py attaches scenarios to the root for the same
        # effect; here it is one batched solve).
        st0 = pdhg.solve(b.qp, opts.sub_pdhg,
                         pdhg.init_state(b.qp, opts.sub_pdhg))
        ws_dual = boxqp.dual_objective(b.qp, st0.x, st0.y)
        ws = float(b.expectation(ws_dual))
        if opts.eta_lb is not None:
            eta_lb = opts.eta_lb
        elif opts.multicut:
            # per-scenario eta_s needs a PER-SCENARIO valid lower bound:
            # the expectation is NOT below every scenario's own value
            wsd = np.asarray(ws_dual, np.float64)
            eta_lb = wsd - 0.05 * np.abs(wsd) - 1.0
            eta_lb[~real] = 0.0  # padded scenarios: p=0, keep bounded
        else:
            eta_lb = ws - 0.05 * abs(ws) - 1.0
        x_non0 = b.nonants(st0.x)
        xhat = np.asarray(jnp.sum(b.p[:, None] * x_non0, axis=0), np.float64)
        xhat = np.clip(xhat, self._x_l, self._x_u)

        # host-side master cut buffer (float64; static device shapes)
        cuts_A = np.zeros((opts.max_cuts, ncols))
        cuts_bl = np.full(opts.max_cuts, -np.inf)
        cuts_bu = np.full(opts.max_cuts, np.inf)
        ncuts = 0

        def add_row(row, bl=-np.inf, bu=np.inf):
            nonlocal ncuts
            if ncuts >= opts.max_cuts:
                # overwrite the oldest cut (simple ring; the reference
                # keeps all cuts — capacity is a device-shape tradeoff)
                idx = ncuts % opts.max_cuts
            else:
                idx = ncuts
            cuts_A[idx] = row
            cuts_bl[idx] = bl
            cuts_bu[idx] = bu
            ncuts += 1

        self.lb, self.ub = -np.inf, np.inf
        best_xhat = xhat.copy()
        for t in range(1, opts.max_iter + 1):
            self.iterations = t
            res = _subproblem_cuts(b, jnp.asarray(xhat, b.qp.c.dtype),
                                   opts.sub_pdhg)
            status = np.asarray(res["status"])
            infeas = real & (status == pdhg.INFEASIBLE)
            cuts_before = ncuts
            if infeas.any():
                # feasibility cuts for every certified-infeasible scenario
                consts = np.asarray(res["feas_const"], np.float64)
                gfs = np.asarray(res["feas_g"], np.float64)
                qvals = np.asarray(res["feas_qval"], np.float64)
                for s in np.nonzero(infeas)[0]:
                    if not np.isfinite(qvals[s]) or qvals[s] <= 0.0:
                        continue  # no usable affine certificate
                    row = np.zeros(ncols)
                    row[:N] = gfs[s]
                    add_row(row, bu=-consts[s])
                if ncuts == cuts_before:
                    # no usable certificate from any infeasible scenario:
                    # the master would re-solve the identical problem —
                    # bail instead of livelocking to max_iter
                    global_toc("LShaped: infeasible subproblem(s) with no "
                               "usable Farkas certificate; stopping", True)
                    break
            else:
                # inner bound: primal objective is valid when every real
                # scenario is primal-feasible at tolerance
                rp = np.asarray(res["rp"])
                obj = np.asarray(res["obj"], np.float64)
                if np.all(rp[real] <= opts.feas_tol):
                    ub_t = float(np.sum(self._p * obj))
                    if ub_t < self.ub:
                        self.ub = ub_t
                        best_xhat = xhat.copy()
                # optimality cut(s)
                alpha = np.asarray(res["alpha"], np.float64)
                gmat = np.asarray(res["g"], np.float64)
                if opts.multicut:
                    for s in np.nonzero(real)[0]:
                        row = np.zeros(ncols)
                        row[:N] = -gmat[s]
                        row[N + s] = 1.0
                        add_row(row, bl=alpha[s])
                else:
                    gbar = np.sum(self._p[:, None] * gmat, axis=0)
                    abar = float(np.sum(self._p * alpha))
                    row = np.zeros(ncols)
                    row[:N] = -gbar
                    row[N] = 1.0
                    add_row(row, bl=abar)

            qp_m, scal = self._master_qp(cuts_A, cuts_bl, cuts_bu, eta_lb)
            xm, val, lb_m, rd_m, done = _master_solve(qp_m,
                                                      opts.master_pdhg)
            x_orig = np.asarray(xm, np.float64) * scal.d_col
            xhat = np.clip(x_orig[:N], self._x_l, self._x_u)
            if float(rd_m) <= 10.0 * opts.master_pdhg.tol:
                self.lb = max(self.lb, float(lb_m))

            gap = self.ub - self.lb
            rel = gap / max(1e-10, abs(self.ub)) if np.isfinite(gap) \
                else np.inf
            self.trace.append(dict(iter=t, lb=self.lb, ub=self.ub,
                                   rel_gap=rel, ncuts=min(ncuts,
                                                          opts.max_cuts)))
            global_toc(f"LShaped iter {t}: lb {self.lb:.6g} "
                       f"ub {self.ub:.6g} rel_gap {rel:.3e}",
                       opts.display_progress)
            if self.spcomm is not None:
                # publish the FRESH master candidate (not the stale
                # incumbent): the xhat-lshaped spoke's whole job is to
                # evaluate candidates the hub has not certified yet
                self.xhat = xhat.copy()
                self.spcomm.sync()
                if self.spcomm.is_converged():
                    break
            if rel <= opts.tol:
                break

        self.xhat = best_xhat
        return dict(bound=self.lb, ub=self.ub, xhat=best_xhat,
                    iterations=self.iterations, trace=self.trace)

    # -- solution access (parity with PH driver) ---------------------------
    def first_stage_solution(self) -> np.ndarray:
        return self.xhat

    def nonant_values(self) -> np.ndarray:
        return self.xhat[None, :]
