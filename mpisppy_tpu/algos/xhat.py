###############################################################################
# Xhat evaluation and inner-bound heuristics.
#
# The reference's Xhat_Eval (ref:mpisppy/utils/xhat_eval.py:33-400) fixes
# candidate first-stage values into every scenario model and solves for
# the recourse, giving E[f(xhat, xi_s)] — an upper (inner) bound for min
# problems.  Its xhat spokes try candidates: xbar (rounded for integers,
# ref:mpisppy/extensions/xhatxbar.py + cylinders/xhatxbar_bounder.py:37),
# individual scenarios' own first-stage values shuffled
# (ref:mpisppy/cylinders/xhatshufflelooper_bounder.py:23-157), and
# slamming every nonant to the scenario-max/min
# (ref:mpisppy/cylinders/slam_heuristic.py:25-129).
#
# TPU-native, a candidate evaluation is one batched solve of the SAME
# scenario tensors with the nonant box collapsed to the candidate point,
# and K candidates batch again on a leading axis via vmap — the whole
# "shuffle looper" is a single (K, S)-shaped program, not a process.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.core.batch import ScenarioBatch, concretize
from mpisppy_tpu.ops import boxqp, pdhg

Array = jax.Array

# Safety factor on the first-order infeasibility compensation
# E[sum |y| viol]: the compensation uses the current (truncated-solve)
# dual iterate, not a verified dual bound, so the exact-penalty
# inequality f* <= f(x) + ||y*||'viol need not hold exactly — the
# published inner bounds are APPROXIMATELY certified, with error
# O(rp * |y - y*|).  Doubling the measured compensation covers the
# inexact-dual slack at first order; the comp-tightness gate
# (comp_tight / fused_wheel._eval_step) bounds how much of the value
# the (scaled) compensation may be, so the slack stays a vanishing
# fraction of the bound.  Exactly feasible solves pay zero either way.
COMP_SAFETY = 2.0

# Max expected compensation relative to the value a published inner
# bound may carry (the gate every publication path enforces — matches
# fused_wheel.FusedWheelOptions.xhat_comp_tol and EFXhatInnerBound).
DEFAULT_COMP_TOL = 2e-3


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["value", "per_scenario", "feasible", "primal_resid",
                 "status", "comp"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class XhatResult:
    value: Array         # () E[f(xhat)]; +inf when infeasible
    per_scenario: Array  # (S,) recourse objective values
    feasible: Array      # () bool — every real scenario feasible at tol
    primal_resid: Array  # (S,) relative primal residuals
    status: Array        # (S,) int32 pdhg status (INFEASIBLE certified)
    comp: Array          # (S,) safety-scaled first-order infeasibility
    #                      compensation already INCLUDED in per_scenario


def comp_tight_mask(values, ecomps,
                    comp_tol: float = DEFAULT_COMP_TOL) -> np.ndarray:
    """Vectorized publication tightness gate — THE single host-side
    source of the formula (comp_tight and the batched shuffle harvest
    both call it; fused_wheel._eval_step is the in-graph twin): finite
    value AND E[comp] <= comp_tol * max(1, |value|)."""
    values = np.asarray(values, np.float64)
    ecomps = np.asarray(ecomps, np.float64)
    return np.isfinite(values) \
        & (ecomps <= comp_tol * np.maximum(1.0, np.abs(values)))


def comp_tight(batch: ScenarioBatch, res: XhatResult,
               comp_tol: float = DEFAULT_COMP_TOL) -> bool:
    """Publication tightness gate (host-side): the compensation is
    first-order, so a value whose compensation is a material fraction
    of the bound itself is not trustworthy (hydro measured +37% at
    stiff duals).  Matches fused_wheel._eval_step's in-loop gate —
    callers check this before offering res.value as an incumbent."""
    return bool(comp_tight_mask(float(res.value),
                                float(batch.expectation(res.comp)),
                                comp_tol))


def evaluate_warm(batch: ScenarioBatch, xhat: Array,
                  solver: pdhg.PDHGState,
                  opts: pdhg.PDHGOptions = pdhg.PDHGOptions(),
                  feas_tol: float = 1e-3):
    """Warm evaluate with the same stalled-tail rescue as evaluate():
    scenarios the warm solve leaves unconverged are re-solved cold at
    the rescue profile and the better per-scenario results merged.  The
    returned warm state is always the PRIMARY solve's (next sync warms
    from it either way)."""
    res, st = _evaluate_warm_core(batch, xhat, solver, opts, feas_tol)
    return _rescue_merge(batch, xhat, res, opts, feas_tol), st


@partial(jax.jit, static_argnames=("opts", "feas_tol"))
def _evaluate_warm_core(batch: ScenarioBatch, xhat: Array,
                        solver: pdhg.PDHGState,
                        opts: pdhg.PDHGOptions = pdhg.PDHGOptions(),
                        feas_tol: float = 1e-3):
    """evaluate() carrying PDHG state across calls — candidates change
    little between hub syncs, so reusing iterates + step-size machinery
    cuts the per-sync solve cost (the round-2 review's 'xhat_shuffle
    re-inits cold per candidate' weakness #7; the reference's loopers
    reuse warm per-scenario solver state the same way,
    ref:mpisppy/cylinders/xhatshufflelooper_bounder.py warm Xhat_Eval).
    Returns (XhatResult, new_solver_state)."""
    batch = concretize(batch)  # scengen: synthesize in-trace
    qp = batch.with_fixed_nonants(xhat)
    opts = dataclasses.replace(opts, detect_infeas=True)
    st = dataclasses.replace(
        solver,
        x=jnp.clip(solver.x, qp.l, qp.u))
    st = pdhg.solve(qp, opts, st)
    # first-order infeasibility compensation — see _evaluate_core
    obj = jnp.sum(qp.c * st.x + 0.5 * qp.q * st.x * st.x, axis=-1)
    comp = COMP_SAFETY * jnp.sum(
        jnp.abs(st.y) * boxqp.primal_residual(qp, st.x), axis=-1)
    obj = obj + comp
    rp, _, _ = boxqp.kkt_residuals(qp, st.x, st.y)
    real = batch.p > 0.0
    scen_ok = (rp <= feas_tol) & (st.status != pdhg.INFEASIBLE) \
        & (st.status != pdhg.UNBOUNDED)
    feas = jnp.all(jnp.where(real, scen_ok, True))
    value = jnp.where(feas, batch.expectation(obj),
                      jnp.asarray(jnp.inf, obj.dtype))
    return XhatResult(value=value, per_scenario=obj, feasible=feas,
                      primal_resid=rp, status=st.status, comp=comp), st


def evaluate(batch: ScenarioBatch, xhat: Array,
             opts: pdhg.PDHGOptions = pdhg.PDHGOptions(),
             feas_tol: float = 1e-3) -> XhatResult:
    """_evaluate_core plus a RESCUE pass: a small tail of degenerate
    recourse LPs (~0.3% of sslp scenarios at 10k, measured) stalls under
    the default primal weight omega0=1 — their residual even grows with
    more iterations — but converges cleanly at omega0=0.1 with longer
    restart windows.  When any real scenario misses tolerance, re-solve
    once with the rescue profile and keep each scenario's better
    result; both profiles compile once."""
    res = _evaluate_core(batch, xhat, opts, feas_tol)
    return _rescue_merge(batch, xhat, res, opts, feas_tol)


def _scen_ok(res: XhatResult, feas_tol: float):
    return (res.primal_resid <= feas_tol) \
        & (res.status != pdhg.INFEASIBLE) \
        & (res.status != pdhg.UNBOUNDED)


# (omega0, restart_period, max_iters multiplier) rescue tiers, tried in
# order until every real scenario clears tolerance
_RESCUE_TIERS = ((0.1, 80, 3), (0.03, 160, 8))


def _rescue_merge(batch: ScenarioBatch, xhat: Array, res: XhatResult,
                  opts: pdhg.PDHGOptions, feas_tol: float) -> XhatResult:
    """NOTE: reads device results (blocking) — call from host-level
    evaluation paths or a spoke's HARVEST, never from Spoke.update."""
    if bool(res.feasible):
        return res
    ok = _scen_ok(res, feas_tol)
    per, rp, status = res.per_scenario, res.primal_resid, res.status
    comp = res.comp
    real = batch.p > 0.0
    # re-solving only helps UNCONVERGED scenarios; a certified
    # Farkas/recession status cannot improve, so skip the (expensive)
    # rescue solves when only certified-infeasible scenarios fail
    rescueable = real & ~ok & (status != pdhg.INFEASIBLE) \
        & (status != pdhg.UNBOUNDED)
    if not bool(jnp.any(rescueable)):
        return res
    for om, rper, mul in _RESCUE_TIERS:
        # cap the rescue budget: a single >~100k-iteration while_loop
        # dispatch can outlive the TPU worker's patience (observed
        # worker crash at 320k); 60k is ample for the rescue profiles
        rescue = dataclasses.replace(
            opts, omega0=om, restart_period=rper,
            max_iters=min(mul * opts.max_iters, 60_000))
        r2 = _evaluate_core(batch, xhat, rescue, feas_tol)
        ok2 = _scen_ok(r2, feas_tol)
        # adopt the rescue's result ONLY where it actually converged —
        # a certified-INFEASIBLE status or a near-miss residual must not
        # be clobbered by a tier that diverged for that scenario
        newly = ~ok & ok2
        per = jnp.where(newly, r2.per_scenario, per)
        rp = jnp.where(newly, r2.primal_resid, rp)
        status = jnp.where(newly, r2.status, status)
        comp = jnp.where(newly, r2.comp, comp)
        ok = ok | ok2
        if bool(jnp.all(jnp.where(real, ok, True))):
            break
    feas = jnp.all(jnp.where(real, ok, True))
    value = jnp.where(feas, batch.expectation(per),
                      jnp.asarray(jnp.inf, per.dtype))
    return XhatResult(value=value, per_scenario=per, feasible=feas,
                      primal_resid=rp, status=status, comp=comp)


@partial(jax.jit, static_argnames=("opts", "feas_tol"))
def _evaluate_core(batch: ScenarioBatch, xhat: Array,
                   opts: pdhg.PDHGOptions = pdhg.PDHGOptions(),
                   feas_tol: float = 1e-3) -> XhatResult:
    """E[f(xhat, xi_s)] with nonants fixed to `xhat` ((N,) root-only or
    (num_nodes, N) per-node) — ref:mpisppy/utils/xhat_eval.py:254-340
    (evaluate = _fix_nonants + solve_loop + Eobjective).
    Infeasibility (recourse cannot satisfy constraints) is detected two
    ways, mirroring the reference's per-subproblem status handling
    (ref:mpisppy/spopt.py:76-96,194-231): a certified per-scenario
    Farkas certificate from the kernel (status mask), and the relative
    primal residual exceeding `feas_tol` as a backstop.  An infeasible
    scenario poisons only the scalar `value`, not the per-scenario
    vector — the batch is not poisoned."""
    batch = concretize(batch)  # scengen: synthesize in-trace
    qp = batch.with_fixed_nonants(xhat)
    opts = dataclasses.replace(opts, detect_infeas=True)
    st = pdhg.solve(qp, opts, pdhg.init_state(qp, opts))
    # Original-space objective: scaled c,q absorb the column scaling.
    # First-order infeasibility compensation (+COMP_SAFETY * E[sum |y|
    # viol]): an rp-tolerant "feasible" x can undershoot the true
    # recourse optimum by ~|y*|'viol, so the published inner value is
    # pushed up by that (safety-scaled) margin — zero at exact
    # feasibility (same rule as the fused planes,
    # algos/fused_wheel._eval_step).  The result is APPROXIMATELY
    # certified (see COMP_SAFETY); callers gate publication on
    # comp_tight.
    obj = jnp.sum(qp.c * st.x + 0.5 * qp.q * st.x * st.x, axis=-1)
    comp = COMP_SAFETY * jnp.sum(
        jnp.abs(st.y) * boxqp.primal_residual(qp, st.x), axis=-1)
    obj = obj + comp
    rp, _, _ = boxqp.kkt_residuals(qp, st.x, st.y)
    real = batch.p > 0.0
    # UNBOUNDED is excluded too: a frozen partially-converged iterate of
    # an unbounded recourse has an arbitrary finite objective that must
    # not become an incumbent value.
    scen_ok = (rp <= feas_tol) & (st.status != pdhg.INFEASIBLE) \
        & (st.status != pdhg.UNBOUNDED)
    feas = jnp.all(jnp.where(real, scen_ok, True))
    value = jnp.where(feas, batch.expectation(obj),
                      jnp.asarray(jnp.inf, obj.dtype))
    return XhatResult(value=value, per_scenario=obj, feasible=feas,
                      primal_resid=rp, status=st.status, comp=comp)


def round_integers(batch: ScenarioBatch, xhat: Array,
                   mode: str = "nearest") -> Array:
    """Round integer nonant slots (ref:mpisppy/extensions/xhatxbar.py's
    rounding of xbar for integer variables).

    `mode` selects the rounding direction — "nearest" (the reference's
    behavior), "ceil", or "floor".  The directional modes exist for the
    candidate-tiering escalation in the fused x̄ plane: on models where
    nearest-rounding yields recourse-infeasible candidates (e.g. sslp —
    rounding a fractional server-open variable down can strand client
    demand), ceil opens every fractionally-open facility and lands a
    feasible, if conservative, incumbent.  Validity is unaffected:
    every candidate still passes the recourse evaluator's feasibility
    gate before its value counts."""
    if mode == "nearest":
        rounded = jnp.round(xhat)
    elif mode == "ceil":
        # 1e-2 dust guard: PH x̄ carries float noise, and a bare ceil
        # would "open" every slot sitting at +1e-7
        rounded = jnp.ceil(xhat - 1e-2)
    elif mode == "floor":
        rounded = jnp.floor(xhat + 1e-2)
    else:  # pragma: no cover - guarded by static call sites
        raise ValueError(f"unknown rounding mode: {mode}")
    return jnp.where(batch.integer_slot, rounded, xhat)


def xhat_xbar(batch: ScenarioBatch, xbar_nodes: Array,
              opts: pdhg.PDHGOptions = pdhg.PDHGOptions()) -> XhatResult:
    """Try x̂ = x̄ (integers rounded) — the XhatXbar inner bound
    (ref:mpisppy/cylinders/xhatxbar_bounder.py:37).  Host-level so the
    stalled-tail rescue in evaluate() applies."""
    return evaluate(batch, round_integers(batch, xbar_nodes), opts)


@partial(jax.jit, static_argnames=("opts", "k"))
def xhat_shuffle(batch: ScenarioBatch, x_non: Array, scen_ids: Array,
                 k: int, opts: pdhg.PDHGOptions = pdhg.PDHGOptions()):
    """Try k candidate scenarios' own nonant vectors as x̂, all at once.

    x_non: (S, N) current per-scenario nonants; scen_ids: (k,) candidate
    indices (host supplies the deterministic shuffle, seed 42, matching
    ref:mpisppy/cylinders/xhatshufflelooper_bounder.py:61-99).  Returns
    (values (k,), feasible (k,), cands (k, N), comps (k,)) — the host
    picks the best; cands is the (rounded) candidate tensor actually
    evaluated, so callers never recompute it; comps is each value's
    expected first-order compensation for the comp_tight gate.  The
    reference tries candidates one at a time across ranks; here the K
    trials batch into one (k*S)-subproblem program.
    """
    batch = concretize(batch)  # scengen: synthesize in-trace
    cands = round_integers(batch, x_non[scen_ids])  # (k, N)

    def one(xhat):
        r = _evaluate_core(batch, xhat, opts)
        return r.value, r.feasible, batch.expectation(r.comp)

    values, feas, comps = jax.vmap(one)(cands)
    return values, feas, cands, comps


def slam_candidate(batch: ScenarioBatch, x_non: Array,
                   sense_max: bool) -> Array:
    """(N,) candidate from slamming each nonant to its across-scenario
    max (ceil for integers) or min (floor) — device computation."""
    big = jnp.asarray(jnp.inf, x_non.dtype)
    mask = (batch.p > 0.0)[:, None]
    if sense_max:
        xhat = jnp.max(jnp.where(mask, x_non, -big), axis=0)
        return jnp.where(batch.integer_slot, jnp.ceil(xhat), xhat)
    xhat = jnp.min(jnp.where(mask, x_non, big), axis=0)
    return jnp.where(batch.integer_slot, jnp.floor(xhat), xhat)


def slam_heuristic(batch: ScenarioBatch, x_non: Array, sense_max: bool,
                   opts: pdhg.PDHGOptions = pdhg.PDHGOptions()) -> XhatResult:
    """Slam every nonant to its across-scenario max (or min) and evaluate
    (ref:mpisppy/cylinders/slam_heuristic.py:25-129).  Host-level so the
    stalled-tail rescue in evaluate() applies."""
    return evaluate(batch, slam_candidate(batch, x_non, sense_max), opts)


class XhatEval:
    """Host-side evaluator with the reference Xhat_Eval surface
    (ref:mpisppy/utils/xhat_eval.py:33): evaluate(nonant_cache),
    evaluate_one, calculate_incumbent."""

    def __init__(self, batch: ScenarioBatch,
                 opts: pdhg.PDHGOptions = pdhg.PDHGOptions()):
        self.batch = batch
        self.opts = opts

    def evaluate_one(self, xhat) -> float:
        return float(evaluate(self.batch, jnp.asarray(xhat), self.opts).value)

    def evaluate(self, xhat) -> float:
        return self.evaluate_one(xhat)

    def calculate_incumbent(self, candidates) -> tuple[float, int]:
        """Best (value, index) over a list of candidates
        (ref:mpisppy/utils/xhat_eval.py:368)."""
        vals = [self.evaluate_one(x) for x in candidates]
        best = int(min(range(len(vals)), key=lambda i: vals[i]))
        return vals[best], best
