###############################################################################
# Progressive Hedging, TPU-native.
#
# The reference's PH (ref:mpisppy/phbase.py, ref:mpisppy/opt/ph.py) is a
# Python loop over per-scenario Pyomo models: Compute_Xbar does one MPI
# Allreduce per tree node, Update_W is a loop over Pyomo Params, and
# solve_loop dispatches each subproblem to a CPU MIP solver
# sequentially.  Here ONE jitted step does all of it as tensor math over
# the scenario batch:
#
#   x_non   = gather nonants from the batched PDHG iterates   (S, N)
#   xbar    = node_average(x_non)           <- the psum/Allreduce analog
#   W      += rho * (x_non - xbar)          (ref:phbase.py:301-326)
#   conv    = E[ ||x_non - xbar||_1 ] / N   (ref:phbase.py:349-371)
#   qp_eff  = base qp + W·x + rho/2 (x - xbar)^2 on nonant slots
#             (ref:phbase.py:670-760 — exact diagonal prox, no
#              linearization cuts needed: the kernel natively solves QPs)
#   solver  = solve_fixed(qp_eff, n_windows) warm-started
#
# The step is compiled once and runs identically on 1 device or a pod
# mesh — scenario-axis reductions become XLA all-reduces via sharding.
# Iteration semantics match the reference: Iter0 solves WITHOUT W/prox
# and seeds W = rho(x - xbar) (ref:phbase.py:829-946); the trivial bound
# is the wait-and-see expectation E[min f_s] (ref:spopt.py:377).
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu import global_toc
from mpisppy_tpu.core.batch import ScenarioBatch, concretize
from mpisppy_tpu.ops import pdhg
from mpisppy_tpu.telemetry import profiler as _prof

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class PHOptions:
    """Static PH options (ref Config group ph_args,
    ref:mpisppy/utils/config.py:250-315)."""

    default_rho: float = 1.0
    max_iterations: int = 100
    conv_thresh: float = 1e-4          # ref 'convthresh'
    subproblem_windows: int = 8        # PDHG restart windows per PH iter
    iter0_windows: int = 400           # budget for the cold iter0 solves
    pdhg: pdhg.PDHGOptions = pdhg.PDHGOptions(tol=1e-6)
    smoothed: bool = False             # ref 'smoothed' / Update_z
    smooth_beta: float = 0.2           # ref 'defaultPHbeta'
    smooth_p: float = 0.0              # ref 'defaultPHp' (coef of (x-z)^2/2)
    compute_xsqbar: bool = False       # node avg of x^2 (fixer variance test)
    display_progress: bool = False
    time_limit: float | None = None


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["solver", "W", "z", "xbar", "xbar_nodes", "xsqbar", "conv",
                 "rho"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class PHState:
    solver: pdhg.PDHGState  # scaled-space subproblem iterates
    W: Array                # (S, N) duals, original space
    z: Array                # (S, N) smoothing state (unused unless smoothed)
    xbar: Array             # (S, N) per-scenario view of node averages
    xbar_nodes: Array       # (num_nodes, N) node averages
    xsqbar: Array           # (S, N) node avg of x^2 (zeros unless enabled)
    conv: Array             # () scaled ||x - xbar||_1
    rho: Array              # (N,) per-slot penalty


def _xbar_w_conv(batch: ScenarioBatch, st: PHState, beta: float,
                 smoothed: bool, compute_xsqbar: bool):
    """Compute_Xbar + Update_W (+Update_z) + convergence_diff, fused.

    Semantics match ref:mpisppy/phbase.py:301-371: W += rho*(x - xbar)
    always; smoothing only updates z += beta*(x - z) (the (x-z)^2 term
    enters the objective separately).  The convergence metric is the
    probability-weighted mean of ||x - xbar||_1 per slot — identical to
    the reference's unweighted mean for uniform probabilities, and the
    correct generalization otherwise (padded p=0 scenarios drop out).
    xsqbar (the fixer variance statistic, ref:phbase.py:60-66) costs an
    extra segmented reduction, so it is only computed when an extension
    asks for it (compute_xsqbar).
    """
    x_non = batch.nonants(st.solver.x)
    xbar, xbar_nodes = batch.node_average(x_non)
    if compute_xsqbar:
        xsqbar, _ = batch.node_average(x_non * x_non)
    else:
        xsqbar = st.xsqbar
    W = st.W + st.rho * (x_non - xbar)
    if batch.var_prob is not None:
        # variable probability: mask W and the convergence metric on
        # absent (weight-0) slots (ref:mpisppy/spbase.py:398-441
        # prob0_mask; ref:aph.py W *= prob0_mask)
        mask = (batch.var_prob > 0.0).astype(W.dtype)
        W = W * mask
        conv = jnp.sum(batch.var_prob * jnp.abs(x_non - xbar)) \
            / batch.num_nonants
    else:
        conv = batch.expectation(
            jnp.sum(jnp.abs(x_non - xbar), axis=-1)) / batch.num_nonants
    z = (1.0 - beta) * st.z + beta * x_non if smoothed else st.z
    return x_non, xbar, xbar_nodes, xsqbar, W, z, conv


def _prox_qp(batch: ScenarioBatch, W: Array, xbar: Array, z: Array,
             rho: Array, smooth_p: float):
    """base objective + W·x + rho/2 (x-xbar)^2 [+ p/2 (x-z)^2] on nonant
    slots (ref:mpisppy/phbase.py:670-760, exact instead of cut-based)."""
    lin = W - rho * xbar - smooth_p * z
    quad = jnp.broadcast_to(rho + smooth_p, xbar.shape)
    return batch.with_nonant_linear_quad(lin, quad)


def iter0_solve_and_certify(batch: ScenarioBatch, windows: int,
                            pdhg_opts: pdhg.PDHGOptions):
    """Plain (no W, no prox) scenario solves + dual-certified trivial
    bound — shared by PH and APH Iter0.

    The trivial bound (wait-and-see expectation, ref:spopt.py:377) is
    taken from the DUAL side with a residual certificate: a truncated
    primal iterate can overshoot the scenario optimum, which would make
    E[obj] an INVALID outer bound; the Fenchel dual value at a
    dual-feasible iterate is always valid.  Returns
    (solver_state, trivial_bound, certified)."""
    from mpisppy_tpu.ops import boxqp as _boxqp
    st0 = pdhg.init_state(batch.qp, pdhg_opts)
    solver = pdhg.solve_fixed(batch.qp, windows, pdhg_opts, st0)
    dual = _boxqp.dual_objective(batch.qp, solver.x, solver.y)
    _, rd, _ = _boxqp.kkt_residuals(batch.qp, solver.x, solver.y)
    tol = jnp.maximum(pdhg_opts.tol, 5.0 * jnp.finfo(solver.x.dtype).eps)
    real = batch.p > 0.0
    certified = jnp.all(jnp.where(real, rd <= 10.0 * tol, True))
    return solver, batch.expectation(dual), certified


def kernel_opts(opts: PHOptions) -> PHOptions:
    """Normalize host-loop-only fields (iteration caps, display, time
    limits) to fixed values before an options object becomes a jit
    static argument: they do not affect the compiled program, and
    letting them into the hash caused spurious recompiles (a multi-
    minute remote compile per distinct max_iterations value)."""
    return dataclasses.replace(
        opts, default_rho=0.0, max_iterations=0, conv_thresh=0.0,
        display_progress=False, time_limit=None)


@partial(jax.jit, static_argnames=("opts",))
def ph_iter0(batch: ScenarioBatch, rho: Array, opts: PHOptions):
    """Iter0: plain scenario solves, xbar, W seed, trivial bound
    (ref:mpisppy/phbase.py:829-946).  Returns
    (state, trivial_bound, certified)."""
    batch = concretize(batch)  # scengen: synthesize in-trace
    solver, trivial_bound, certified = iter0_solve_and_certify(
        batch, opts.iter0_windows, opts.pdhg)
    zeros = jnp.zeros((batch.num_scenarios, batch.num_nonants),
                      batch.qp.c.dtype)
    zeros_nodes = jnp.zeros((batch.tree.num_nodes, batch.num_nonants),
                            batch.qp.c.dtype)
    st = PHState(solver=solver, W=zeros, z=zeros, xbar=zeros,
                 xbar_nodes=zeros_nodes, xsqbar=zeros,
                 conv=jnp.asarray(jnp.inf, batch.qp.c.dtype), rho=rho)
    x_non, xbar, xbar_nodes, xsqbar, W, z, conv = _xbar_w_conv(
        batch, st, opts.smooth_beta, False, opts.compute_xsqbar)
    return (dataclasses.replace(st, W=W, xbar=xbar, xbar_nodes=xbar_nodes,
                                xsqbar=xsqbar, conv=conv),
            trivial_bound, certified)


@partial(jax.jit, static_argnames=("opts",))
def ph_iterk(batch: ScenarioBatch, st: PHState, opts: PHOptions) -> PHState:
    """One PH iteration: solve subproblems with current (W, xbar), then
    refresh xbar/W/conv from the new iterates
    (ref:mpisppy/phbase.py:949-1061, with xbar computed *after* the
    solves so the returned state is self-consistent)."""
    batch = concretize(batch)  # scengen: synthesize in-trace
    smooth_p = opts.smooth_p if opts.smoothed else 0.0
    qp_eff = _prox_qp(batch, st.W, st.xbar, st.z, st.rho, smooth_p)
    solver = pdhg.solve_fixed(qp_eff, opts.subproblem_windows, opts.pdhg,
                              st.solver)
    st = dataclasses.replace(st, solver=solver)
    x_non, xbar, xbar_nodes, xsqbar, W, z, conv = _xbar_w_conv(
        batch, st, opts.smooth_beta, opts.smoothed, opts.compute_xsqbar)
    return dataclasses.replace(st, W=W, z=z, xbar=xbar,
                               xbar_nodes=xbar_nodes, xsqbar=xsqbar,
                               conv=conv)


@jax.jit
def ph_eobjective(batch: ScenarioBatch, st: PHState) -> Array:
    """E[f_s(x_s)] at current iterates (ref:mpisppy/spopt.py:344-376)."""
    batch = concretize(batch)
    return batch.expectation(batch.objective(st.solver.x))


class PH:
    """Host-side PH driver (ref:mpisppy/opt/ph.py:24-76).

    Supports the reference's extension plane: `extensions` is an object
    (or class) with the hook methods of ref:mpisppy/extensions/extension.py;
    missing hooks are skipped.  `converger` gets is_converged(self).
    `spcomm` (set by the cylinder layer) gets sync()/is_converged().
    """

    def __init__(self, options: PHOptions, batch: ScenarioBatch,
                 scenario_names=None, rho: Array | float | None = None,
                 extensions=None, converger=None, rho_setter=None):
        self.options = options
        self.batch = batch
        self.scenario_names = scenario_names or [
            f"scen{i}" for i in range(batch.num_real)]
        if rho is None:
            rho = options.default_rho
        rho_arr = jnp.broadcast_to(
            jnp.asarray(rho, batch.qp.c.dtype), (batch.num_nonants,))
        if rho_setter is not None:
            rho_arr = jnp.asarray(rho_setter(batch), batch.qp.c.dtype)
        self.rho = rho_arr
        # `extensions`/`converger` may be a class, a factory taking the
        # driver (e.g. functools.partial(MultiExtension, ext_classes=…)),
        # or an already-built object.
        def _build(thing):
            if thing is None:
                return None
            # classes, functions, and partials are factories taking the
            # driver; built objects (not callable) pass through
            if isinstance(thing, type) or callable(thing):
                return thing(self)
            return thing
        self.extobject = _build(extensions)
        self.converger_object = _build(converger)
        self.spcomm = None
        self.state: PHState | None = None
        self.trivial_bound: float | None = None
        self.trivial_bound_certified: bool = False
        self._iter = 0

    # -- extension callout plumbing (ref:extensions/extension.py:18-151) --
    def _ext(self, hook: str):
        obj = self.extobject
        if obj is not None and hasattr(obj, hook):
            getattr(obj, hook)()

    @property
    def local_scenarios(self):  # parity helper for extensions
        return self.scenario_names

    _label = "PH"

    def state_template(self):
        """Abstract (shape/dtype) pytree of this driver's state — the
        unflatten template for checkpoint restore (hub.load_checkpoint)
        without paying an Iter0 solve."""
        st, _, _ = jax.eval_shape(
            partial(ph_iter0, opts=kernel_opts(self.options)),
            self.batch, self.rho)
        return st

    # -- algorithm step hooks (overridden by APH) -------------------------
    def _iter0_impl(self):
        return ph_iter0(self.batch, self.rho, kernel_opts(self.options))

    def _iterk_impl(self):
        return ph_iterk(self.batch, self.state, kernel_opts(self.options))

    def _iter_msg(self, k: int, conv: float) -> str:
        return f"{self._label} iter {k}: conv = {conv:.3e}"

    def _read_conv(self) -> float:
        """Per-iteration convergence read (one device scalar transfer;
        FusedPH serves it from the packed scalar cache instead)."""
        return float(self.state.conv)

    def Eobjective(self) -> float:
        return float(ph_eobjective(self.batch, self.state))

    def Iter0(self) -> float:
        self._ext("pre_iter0")
        # the batched kernel has no per-scenario solver objects; "solver
        # creation" is the jitted step build, which happens inside
        # _iter0_impl — the hook fires at the reference's point in the
        # sequence (ref:mpisppy/phbase.py:851 after _create_solvers)
        self._ext("iter0_post_solver_creation")
        with _prof.annotate("wheel/iter0_solve"):
            import time as _time
            _t0 = _time.perf_counter()
            self.state, tb, cert = self._iter0_impl()
            _dt = _time.perf_counter() - _t0
        if self.spcomm is not None:
            self.spcomm.emit_span("iter0_solve", _dt)
        self.trivial_bound = float(tb)
        self.trivial_bound_certified = bool(cert)
        self._ext("post_iter0")
        if self.spcomm is not None:
            self.spcomm.sync()
        self._ext("post_iter0_after_sync")
        global_toc(f"{self._label} Iter0: trivial bound = "
                   f"{self.trivial_bound:.6g}",
                   self.options.display_progress)
        return self.trivial_bound

    def iterk_loop(self):
        import time
        t0 = time.time()
        for k in range(self._iter + 1, self.options.max_iterations + 1):
            self._iter = k
            self._ext("miditer")
            # the fused step solves + recomputes xbar/W in one program,
            # so the solve-loop hooks bracket the whole jitted step
            # (ref callout points: mpisppy/phbase.py:1016-1045)
            self._ext("pre_solve_loop")
            with _prof.annotate("wheel/subproblem_solve"):
                t_solve = time.perf_counter()
                self.state = self._iterk_impl()
                dt_solve = time.perf_counter() - t_solve
            if self.spcomm is not None:
                # host wall of the step dispatch; with async XLA the
                # device wait shows up in the next blocking read (the
                # hub's harvest span) — docs/telemetry.md
                self.spcomm.emit_span("subproblem_solve", dt_solve)
            self._ext("post_solve_loop")
            conv = self._read_conv()
            self._ext("enditer")
            if self.spcomm is not None:
                self.spcomm.sync()
            self._ext("enditer_after_sync")
            global_toc(self._iter_msg(k, conv),
                       self.options.display_progress)
            # The hub object takes precedence over the local convergence
            # metric (ref:mpisppy/phbase.py:996-1015 ordering).
            if self.spcomm is not None and self.spcomm.is_converged():
                break
            if (self.converger_object is not None
                    and self.converger_object.is_converged()):
                break
            if conv <= self.options.conv_thresh:
                global_toc(f"{self._label} converged at iter {k} "
                           f"(conv={conv:.3e})",
                           self.options.display_progress)
                if self.spcomm is not None:
                    self.spcomm._term_reason = "conv-thresh"
                break
            if (self.options.time_limit is not None
                    and time.time() - t0 > self.options.time_limit):
                if self.spcomm is not None:
                    self.spcomm._term_reason = "time-limit"
                break
        return float(self.state.conv)

    def post_loops(self) -> float:
        self._ext("post_everything")
        return self.Eobjective()

    def ph_main(self):
        """Returns (conv, Eobj, trivial_bound) (ref:opt/ph.py:31-76).

        Resume: when state was preloaded (checkpoint restore — see
        utils.wxbarutils.load_ph_state and the hub's checkpoint hooks),
        Iter0 is skipped and the loop continues from the restored
        iteration counter — the analog of the reference's
        solve-retry/restart semantics (ref:mpisppy/spopt.py:931-960)."""
        if self.state is None:
            tb = self.Iter0()
        else:
            tb = self.trivial_bound
        conv = self.iterk_loop()
        eobj = self.post_loops()
        return conv, eobj, tb

    # -- solution access (ref:spbase.py:561-672 analogs) -----------------
    def nonant_values(self) -> np.ndarray:
        """(num_nodes, N) converged per-node nonant values (xbar)."""
        return np.asarray(self.state.xbar_nodes)

    def first_stage_solution(self) -> np.ndarray:
        """(n_root_slots,) root-node nonant values."""
        nodes = self.nonant_values()
        root = np.nonzero(self.batch.tree.slot_stage == 1)[0]
        return nodes[0, root]
