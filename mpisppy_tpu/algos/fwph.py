###############################################################################
# Frank-Wolfe Progressive Hedging (FWPH), TPU-native.
#
# Reference behavior (ref:mpisppy/fwph/fwph.py:58-307, Boland et al. 2018
# "Combining Progressive Hedging with a Frank-Wolfe method"): per
# scenario, maintain a set of *columns* (feasible points of X_s); each
# outer iteration runs an SDM (simplicial decomposition) inner loop:
#
#   1. linearization oracle:  v = argmin_{x in X_s} f_s(x) + What·x_non
#      with What = W + rho (x_t - xbar)  (the PH objective's gradient at
#      the current point x_t) — the role the per-scenario MIP solve plays
#      in the reference (fwph.py:247-257);
#   2. at inner iteration 0 this oracle IS the Lagrangian subproblem at a
#      valid multiplier (E_node[What] = 0 because E[x_t] = xbar), so its
#      dual value yields the TRUE dual bound (fwph.py:264-269);
#   3. add v to the column set and re-solve the inner QP
#      min_{lam in Delta} f_s(V'lam) + W·(V'lam)_non
#                         + rho/2 ||(V'lam)_non - xbar||^2
#      (fwph.py:282-287 solves this per scenario with Gurobi);
#   4. Gamma^t = (phi_lin(x_t) - phi_lin(v)) / max(1,|phi_lin(v)|), the
#      FW gap, drives inner termination (fwph.py:259-276).
#
# After the inner loop: xbar <- node_average(x), W += rho (x - xbar) as
# in PH (fwph.py:186-205).
#
# TPU-first re-design — no per-scenario solver objects, no Pyomo
# expression swapping (fwph.py:994-1051 _swap_nonant_vars exists only
# because Pyomo objectives are symbolic):
#   * the column set is a fixed-size ring buffer (S, K, n) with a
#     validity mask — fixed shapes keep the whole outer iteration one
#     compiled program;
#   * the oracle is ONE batched PDHG solve over all scenarios (warm
#     started across iterations);
#   * the inner QP is one batched K-dim simplex QP (ops/simplex_qp.py)
#     with Gram matrices H = V diag(q) V' + V_non diag(rho) V_non'
#     built by batched matmuls (MXU);
#   * bound validity is certified from the oracle's dual residuals, as
#     everywhere else in this framework (no trusting a black-box solver).
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu import global_toc
from mpisppy_tpu.core.batch import ScenarioBatch, concretize
from mpisppy_tpu.ops import boxqp, pdhg, simplex_qp

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FWPHOptions:
    """Static options (ref FW_options, ref:mpisppy/utils/config.py
    fwph_args: fwph_iter_limit / fwph_weight / fwph_conv_thresh)."""

    fw_iter_limit: int = 2       # SDM inner iterations per outer iter
    fw_weight: float = 0.0       # alpha: linearization point mix
    fw_conv_thresh: float = 1e-4  # Gamma threshold (masks oracle updates)
    max_columns: int = 16        # K: column ring-buffer size
    max_iterations: int = 50     # outer iteration limit
    conv_thresh: float = 1e-4    # PH-style convergence on ||x - xbar||
    default_rho: float = 1.0
    oracle_windows: int = 8      # PDHG restart windows per oracle solve
    iter0_windows: int = 400
    qp_iters: int = 300          # FISTA iterations for the simplex QP
    pdhg: pdhg.PDHGOptions = pdhg.PDHGOptions(tol=1e-6)
    display_progress: bool = False
    time_limit: float | None = None


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["cols", "valid", "next_slot", "lam", "x", "W", "xbar",
                 "xbar_nodes", "conv", "rho", "oracle", "bound", "best_bound",
                 "certified", "gamma"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class FWPHState:
    cols: Array        # (S, K, n) scaled-space column buffer
    valid: Array       # (S, K) bool
    next_slot: Array   # () int32 ring-buffer write cursor (shared)
    lam: Array         # (S, K) simplex weights
    x: Array           # (S, n) scaled-space current point V'lam
    W: Array           # (S, N) duals, original space
    xbar: Array        # (S, N)
    xbar_nodes: Array  # (num_nodes, N)
    conv: Array        # () scaled ||x - xbar||_1
    rho: Array         # (N,)
    oracle: pdhg.PDHGState
    bound: Array       # () last outer iteration's dual bound
    best_bound: Array  # () max over certified bounds
    certified: Array   # () bool for `bound`
    gamma: Array       # (S,) last FW gap per scenario


def _phi_parts(batch: ScenarioBatch, W: Array, xbar: Array, rho: Array):
    """Linear/quadratic coefficients of the PH objective
    phi(x) = f_s(x) + W·x_non + rho/2 ||x_non - xbar||^2 in scaled space:
    returns (c_eff (S,n), q_eff (S,n)) with nonant terms scattered in."""
    lin = W - rho * xbar
    quad = jnp.broadcast_to(rho, xbar.shape)
    qp_eff = batch.with_nonant_linear_quad(lin, quad)
    return qp_eff.c, qp_eff.q


def _inner_qp(batch: ScenarioBatch, st: FWPHState):
    """Build the simplex-QP Gram data from the column buffer.

    phi(V'lam) = 1/2 lam' H lam + g' lam + const with
      H = V diag(q_eff) V',  g = V c_eff
    where (c_eff, q_eff) carry f_s + W + prox contributions.
    """
    c_eff, q_eff = _phi_parts(batch, st.W, st.xbar, st.rho)
    S, K, n = st.cols.shape
    Vq = st.cols * q_eff[:, None, :]
    H = jnp.einsum("skn,sjn->skj", Vq, st.cols)
    g = jnp.einsum("skn,sn->sk", st.cols, c_eff)
    return H, g


def _push_column(st: FWPHState, v: Array) -> FWPHState:
    """Add v to each scenario's column set.

    While the buffer has free slots, fill them in order.  Once full,
    evict each scenario's LEAST-WEIGHT column (per-scenario argmin of
    lam) — overwriting in ring order was observed to discard columns
    still carrying large weight, kicking the QP iterate far from
    consensus every K/fw_iter_limit outer iterations (the reference
    never evicts, ref:mpisppy/fwph/fwph.py:309, but an unbounded column
    set is not an option for a fixed-shape compiled program)."""
    S, K, _ = st.cols.shape
    rows = jnp.arange(S)
    slot = jnp.where(
        st.next_slot < K,
        jnp.full((S,), st.next_slot, jnp.int32),
        jnp.argmin(st.lam, axis=-1).astype(jnp.int32),
    )
    cols = st.cols.at[rows, slot].set(v)
    valid = st.valid.at[rows, slot].set(True)
    lam = st.lam.at[rows, slot].set(0.0)
    # renormalize away any (minimal) weight the evicted column carried
    tot = jnp.maximum(jnp.sum(lam, axis=-1, keepdims=True), 1e-12)
    return dataclasses.replace(st, cols=cols, valid=valid, lam=lam / tot,
                               next_slot=st.next_slot + 1)


@partial(jax.jit, static_argnames=("opts",))
def fwph_iter(batch: ScenarioBatch, st: FWPHState,
              opts: FWPHOptions) -> FWPHState:
    """One FWPH outer iteration (Algorithm 3 lines 4-9 of Boland et al.;
    ref:mpisppy/fwph/fwph.py:147-307), fully on device."""
    batch = concretize(batch)  # scengen: synthesize in-trace
    dt = batch.qp.c.dtype
    alpha = jnp.asarray(opts.fw_weight, dt)
    x_non0 = batch.nonants(st.x)
    xt_non = (1.0 - alpha) * st.xbar + alpha * x_non0

    def sdm_step(t, carry):
        st, dual0, cert0, x_non_cur = carry
        x_src = jnp.where(t == 0, xt_non, x_non_cur)
        What = st.W + st.rho * (x_src - st.xbar)
        oracle_qp = batch.with_nonant_linear_quad(
            What, jnp.zeros_like(What))
        oracle = pdhg.solve_fixed(oracle_qp, opts.oracle_windows, opts.pdhg,
                                  st.oracle)
        # dual bound from inner iteration 0 (valid multiplier: see header)
        dual = boxqp.dual_objective(oracle_qp, oracle.x, oracle.y)
        _, rd, _ = boxqp.kkt_residuals(oracle_qp, oracle.x, oracle.y)
        tol = jnp.maximum(opts.pdhg.tol, 5.0 * jnp.finfo(dt).eps)
        real = batch.p > 0.0
        cert = jnp.all(jnp.where(real, rd <= 10.0 * tol, True))
        dual0 = jnp.where(t == 0, batch.expectation(dual), dual0)
        cert0 = jnp.where(t == 0, cert, cert0)

        # Gamma^t: linearized-objective gap between current point and
        # vertex (ref:fwph.py:259-276). phi_lin(x) = f_s(x) + What·x_non.
        v = oracle.x
        c_lin, q_lin = _phi_parts(batch, What,
                                  jnp.zeros_like(st.xbar),
                                  jnp.zeros_like(st.rho))
        def phi_lin(xs):
            return jnp.sum(c_lin * xs + 0.5 * q_lin * xs * xs, axis=-1)
        val_v = phi_lin(v)
        val_x = phi_lin(st.x)
        gamma = (val_x - val_v) / jnp.maximum(1.0, jnp.abs(val_v))

        st = dataclasses.replace(st, oracle=oracle)
        st = _push_column(st, v)
        H, g = _inner_qp(batch, st)
        lam = simplex_qp.solve_simplex_qp(H, g, st.valid, st.lam,
                                          iters=opts.qp_iters)
        x = jnp.einsum("sk,skn->sn", lam, st.cols)
        st = dataclasses.replace(st, lam=lam, x=x, gamma=gamma)
        return st, dual0, cert0, batch.nonants(x)

    init = (st, jnp.asarray(-jnp.inf, dt), jnp.asarray(False), x_non0)
    st, dual0, cert0, x_non = jax.lax.fori_loop(
        0, opts.fw_iter_limit, sdm_step, init)

    # outer updates: xbar, conv, W (ref:fwph.py:186-205 + phbase analogs)
    xbar, xbar_nodes = batch.node_average(x_non)
    conv = batch.expectation(
        jnp.sum(jnp.abs(x_non - xbar), axis=-1)) / batch.num_nonants
    W = st.W + st.rho * (x_non - xbar)
    best = jnp.where(cert0, jnp.maximum(st.best_bound, dual0), st.best_bound)
    return dataclasses.replace(st, xbar=xbar, xbar_nodes=xbar_nodes,
                               conv=conv, W=W, bound=dual0,
                               best_bound=best, certified=cert0)


@partial(jax.jit, static_argnames=("opts",))
def fwph_init(batch: ScenarioBatch, rho: Array, opts: FWPHOptions):
    """fw_prep (ref:mpisppy/fwph/fwph.py:97-145): Iter0-style cold solves
    seed the first column, xbar, and W; the trivial bound comes from the
    dual side with a certificate (same recipe as algos/ph.ph_iter0)."""
    batch = concretize(batch)  # scengen: synthesize in-trace
    dt = batch.qp.c.dtype
    S, N = batch.num_scenarios, batch.num_nonants
    n = batch.qp.c.shape[-1]
    K = opts.max_columns

    st0 = pdhg.init_state(batch.qp, opts.pdhg)
    solver = pdhg.solve_fixed(batch.qp, opts.iter0_windows, opts.pdhg, st0)
    dual = boxqp.dual_objective(batch.qp, solver.x, solver.y)
    _, rd, _ = boxqp.kkt_residuals(batch.qp, solver.x, solver.y)
    tol = jnp.maximum(opts.pdhg.tol, 5.0 * jnp.finfo(dt).eps)
    real = batch.p > 0.0
    cert = jnp.all(jnp.where(real, rd <= 10.0 * tol, True))
    trivial = batch.expectation(dual)

    x = solver.x
    x_non = batch.nonants(x)
    xbar, xbar_nodes = batch.node_average(x_non)
    W = rho * (x_non - xbar)
    conv = batch.expectation(
        jnp.sum(jnp.abs(x_non - xbar), axis=-1)) / N

    cols = jnp.zeros((S, K, n), dt).at[:, 0, :].set(x)
    valid = jnp.zeros((S, K), bool).at[:, 0].set(True)
    lam = jnp.zeros((S, K), dt).at[:, 0].set(1.0)

    st = FWPHState(
        cols=cols, valid=valid, next_slot=jnp.asarray(1, jnp.int32),
        lam=lam, x=x, W=W, xbar=xbar, xbar_nodes=xbar_nodes, conv=conv,
        rho=rho, oracle=solver,
        bound=trivial, best_bound=jnp.where(cert, trivial,
                                            jnp.asarray(-jnp.inf, dt)),
        certified=cert, gamma=jnp.full((S,), jnp.inf, dt),
    )
    return st, trivial, cert


class FWPH:
    """Host-side FWPH driver (ref:mpisppy/fwph/fwph.py:147-212).

    fwph_main() returns (iters, weight_dict, xbar_dict) like the
    reference; the dual bound history is exposed via .best_bound /
    ._local_bound for the spoke layer.
    """

    def __init__(self, options: FWPHOptions, batch: ScenarioBatch,
                 scenario_names=None, rho: Array | float | None = None):
        self.options = options
        self.batch = batch
        self.scenario_names = scenario_names or [
            f"scen{i}" for i in range(batch.num_real)]
        if rho is None:
            rho = options.default_rho
        self.rho = jnp.broadcast_to(
            jnp.asarray(rho, batch.qp.c.dtype), (batch.num_nonants,))
        self.spcomm = None
        self.state: FWPHState | None = None
        self.trivial_bound: float | None = None
        self._local_bound: float = -np.inf
        self.best_bound: float = -np.inf
        self._iter = 0

    def fw_prep(self) -> float:
        self.state, tb, cert = fwph_init(self.batch, self.rho, self.options)
        self.trivial_bound = float(tb)
        if bool(cert):
            self.best_bound = self.trivial_bound
        global_toc(f"FWPH prep: trivial bound = {self.trivial_bound:.6g}",
                   self.options.display_progress)
        return self.trivial_bound

    def fwph_main(self):
        import time
        t0 = time.time()
        self.fw_prep()
        itr = 0
        for itr in range(1, self.options.max_iterations + 1):
            self._iter = itr
            self.state = fwph_iter(self.batch, self.state, self.options)
            self._local_bound = float(self.state.bound)
            self.best_bound = float(self.state.best_bound)
            conv = float(self.state.conv)
            global_toc(
                f"FWPH iter {itr}: bound={self._local_bound:.6g} "
                f"best={self.best_bound:.6g} conv={conv:.3e}",
                self.options.display_progress)
            if self.spcomm is not None:
                self.spcomm.sync()
                if self.spcomm.is_converged():
                    break
            if conv <= self.options.conv_thresh:
                break
            if (self.options.time_limit is not None
                    and time.time() - t0 > self.options.time_limit):
                break
        weights = {nm: np.asarray(self.state.lam[i])
                   for i, nm in enumerate(self.scenario_names)}
        xbars = np.asarray(self.state.xbar_nodes)
        return itr, weights, xbars
