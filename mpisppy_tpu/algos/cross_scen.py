###############################################################################
# Cross-scenario cuts, TPU-native.
#
# The reference pairs a CrossScenarioCutSpoke
# (ref:mpisppy/cylinders/cross_scen_spoke.py:17-303) with a hub
# CrossScenarioExtension (ref:mpisppy/extensions/cross_scen_extension.py:22-433):
# every PH subproblem grows eta_k variables for ALL scenarios plus
# Benders-cut constraints over (x, eta); the spoke picks the hub
# scenario-x farthest from xbar, generates L-shaped cuts from every
# scenario's recourse at that candidate, and the hub periodically solves
# each subproblem with an "EF objective" (own costs + others' etas) for
# a certified outer bound (char 'C').  The cuts' raison d'etre is
# cross-scenario FEASIBILITY pressure (netdes-class problems where one
# scenario's first-stage build under-serves another scenario).
#
# TPU design — two augmented views of the batch, both with STATIC
# preallocated buffers so arriving cuts are functional `.at[].set`
# updates and nothing recompiles:
#
#   * PH view (`augment_rows`): cut ROWS only, no eta columns.  In a PH
#     subproblem an optimality cut "eta_k >= a + g·x" is VACUOUS (eta_k
#     has zero cost there, so it absorbs any x), and carrying S free
#     zero-cost columns measurably degrades PDHG geometry (observed:
#     drifting iterates on the optimal face).  Only FEASIBILITY cuts
#     (pure-x Farkas rows) go into the PH subproblems — they are the
#     cross-scenario feasibility pressure, the mechanism's entire point.
#   * EF view (`augment_ef`): eta columns + ALL cut rows, used only by
#     the periodic bound check.  Subproblem s pins its OWN eta at its
#     lower bound and deactivates its own optimality-cut rows (they are
#     vacuous for s: s enforces its own recourse exactly), removing the
#     free column that stalls the kernel.
#
# Cut generation is one batched fixed-nonant PDHG solve
# (algos.lshaped._subproblem_cuts) — dual-certified optimality cuts and
# Farkas feasibility cuts, valid even for inexact solves.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.core.batch import ScenarioBatch
from mpisppy_tpu.ops import boxqp, pdhg
from mpisppy_tpu.ops.sparse import EllMatrix

Array = jax.Array


@dataclasses.dataclass
class CrossScenMeta:
    """Host bookkeeping: both augmented views + the cut registry."""

    n_orig: int
    m_orig: int
    S: int
    max_rounds: int
    eta_lb: np.ndarray              # (S,)
    aug_ph: ScenarioBatch           # rows-only view (feasibility cuts)
    aug_ef: ScenarioBatch           # eta-columns view (all cuts)
    is_opt: np.ndarray              # (R,) slot holds an optimality cut
    rounds_used: int = 0

    @property
    def R(self) -> int:
        return self.max_rounds * self.S


def _extend_cols(x, fill, width):
    pad = jnp.full(x.shape[:-1] + (width,), fill, x.dtype)
    return jnp.concatenate([x, pad], axis=-1)


def _add_rows(batch: ScenarioBatch, R: int, n_new: int,
              cut_k: int) -> ScenarioBatch:
    """Append R inactive rows (and, for the EF view, n_new eta columns)
    to a batch; cut rows can hold `cut_k` nonzeros in ELL form."""
    qp = batch.qp
    n, m = qp.n, qp.m
    dt = qp.c.dtype
    S = batch.num_scenarios
    N = batch.num_nonants

    c = _extend_cols(qp.c, 0.0, n_new) if n_new else qp.c
    q = _extend_cols(qp.q, 0.0, n_new) if n_new else qp.q
    l = _extend_cols(qp.l, 0.0, n_new) if n_new else qp.l  # noqa: E741
    u = _extend_cols(qp.u, jnp.inf, n_new) if n_new else qp.u
    bl = _extend_cols(qp.bl, -jnp.inf, R)
    bu = _extend_cols(qp.bu, jnp.inf, R)

    if isinstance(qp.A, EllMatrix):
        k_new = max(qp.A.k, cut_k)
        vals, cols = qp.A.vals, qp.A.cols
        if k_new > qp.A.k:
            vals = _extend_cols(vals, 0.0, k_new - qp.A.k)
            cols = jnp.concatenate(
                [cols, jnp.zeros((m, k_new - qp.A.k), cols.dtype)],
                axis=-1)
        # cut-row column pattern: N nonant slots, then (EF view) the
        # round-r scenario-k row's eta column
        pat = [jnp.broadcast_to(batch.nonant_idx, (R, N))]
        if n_new:
            pat.append((n + jnp.tile(jnp.arange(S),
                                     R // S))[:, None])
        pat.append(jnp.zeros((R, k_new - N - (1 if n_new else 0)),
                             batch.nonant_idx.dtype))
        cut_cols = jnp.concatenate(pat, axis=-1).astype(cols.dtype)
        cols = jnp.concatenate([cols, cut_cols], axis=0)
        vals = jnp.concatenate(
            [vals, jnp.zeros(vals.shape[:-2] + (R, k_new), vals.dtype)],
            axis=-2)
        A = EllMatrix(vals=vals, cols=cols, n=n + n_new)
    else:
        bshape = qp.A.shape[:-2]
        A = qp.A
        if n_new:
            A = jnp.concatenate(
                [A, jnp.zeros(bshape + (m, n_new), dt)], axis=-1)
        A = jnp.concatenate(
            [A, jnp.zeros(bshape + (R, n + n_new), dt)], axis=-2)

    d_col = _extend_cols(batch.d_col, 1.0, n_new) if n_new \
        else batch.d_col
    d_row = _extend_cols(batch.d_row, 1.0, R)
    return dataclasses.replace(
        batch,
        qp=dataclasses.replace(qp, c=c, q=q, A=A, bl=bl, bu=bu, l=l, u=u),
        d_col=d_col, d_row=d_row)


def make_meta(batch: ScenarioBatch, eta_lb: np.ndarray,
              max_rounds: int = 8) -> CrossScenMeta:
    """Build both augmented views
    (ref:cross_scen_extension.py:273-300 post_iter0 analog)."""
    S = batch.num_scenarios
    N = batch.num_nonants
    R = max_rounds * S
    aug_ph = _add_rows(batch, R, 0, cut_k=N)
    aug_ef = _add_rows(batch, R, S, cut_k=N + 1)
    l = aug_ef.qp.l
    l = l.at[..., batch.qp.n:].set(
        jnp.asarray(eta_lb, aug_ef.qp.c.dtype))
    aug_ef = dataclasses.replace(
        aug_ef, qp=dataclasses.replace(aug_ef.qp, l=l))
    return CrossScenMeta(n_orig=batch.qp.n, m_orig=batch.qp.m, S=S,
                         max_rounds=max_rounds,
                         eta_lb=np.asarray(eta_lb, np.float64),
                         aug_ph=aug_ph, aug_ef=aug_ef,
                         is_opt=np.zeros(R, bool))


def launch_cuts(batch: ScenarioBatch, nonants: Array, xbar: Array,
                opts: pdhg.PDHGOptions) -> dict:
    """Spoke-side cut generation on the ORIGINAL batch: pick the
    scenario x farthest from xbar (ref:cross_scen_spoke.py:190-230),
    solve every scenario's recourse there (one batched PDHG), return
    DEVICE arrays without blocking (XLA async dispatch)."""
    from mpisppy_tpu.algos.lshaped import _subproblem_cuts
    dist = jnp.linalg.norm(nonants - xbar, axis=-1)
    dist = jnp.where(batch.p > 0.0, dist, -jnp.inf)
    winner = jnp.argmax(dist)
    xhat = nonants[winner]
    cut = _subproblem_cuts(batch, xhat, opts)
    return {"xhat": xhat, **cut}


def package_cuts(raw: dict, opts: pdhg.PDHGOptions) -> dict:
    """Host-side packaging of launch_cuts results (blocks on the
    device values).

    Validity gates: a feasibility cut needs a FINITE usable Farkas
    affine form (qval > 0 with no infinite-bound pairing — the same
    guard lshaped applies); an optimality cut needs the dual residual
    certificate (dual_objective overestimates when rd is large, see its
    docstring).  Scenarios passing neither get `usable=False` and no
    row is written."""
    tol = np.maximum(opts.certificate_tol, 1e-6)
    feas_const = np.asarray(raw["feas_const"])
    feas_g = np.asarray(raw["feas_g"])
    # a separating, finite Farkas form is a valid feasibility cut no
    # matter what the status says (and required even when status says
    # INFEASIBLE — 'bad' rays with infinite-bound pairings are unusable)
    infeas = (np.asarray(raw["feas_qval"]) > tol) \
        & np.isfinite(feas_const) & np.isfinite(feas_g).all(axis=-1)
    rd = np.asarray(raw["rd"])
    rdtol = np.maximum(opts.tol, 5.0 * np.finfo(np.float32).eps)
    opt_ok = rd <= 10.0 * rdtol
    return {
        "xhat": np.asarray(raw["xhat"]),
        "infeas": infeas,
        "usable": infeas | opt_ok,
        "feas_g": feas_g,
        "feas_const": feas_const,
        "opt_g": np.asarray(raw["g"]),
        "opt_alpha": np.asarray(raw["alpha"]),
    }


def _scaled_rows(batch_view: ScenarioBatch, meta: CrossScenMeta,
                 g: np.ndarray, eta_coef: np.ndarray, rhs: np.ndarray):
    """(slot coefficient block, scaled rhs): cut slopes mapped into the
    scaled column space with one inf-norm equilibration scale per cut
    (shared across subproblems so a broadcast bu still works — cut
    coefficient spreads stall the first-order kernel otherwise)."""
    nonant_idx = np.asarray(batch_view.nonant_idx)
    d_all = np.asarray(batch_view.d_col)[..., nonant_idx]
    d_max = d_all if d_all.ndim == 1 else d_all.max(axis=0)
    scale = np.maximum(np.max(np.abs(g) * d_max[None, :], axis=-1),
                       np.abs(eta_coef))
    scale = np.maximum(scale, 1e-8)
    return g / scale[:, None], eta_coef / scale, rhs / scale


def _write_rows(aug: ScenarioBatch, meta: CrossScenMeta, row0: int,
                g: np.ndarray, eta_coef: np.ndarray | None,
                rhs: np.ndarray, active: np.ndarray) -> ScenarioBatch:
    """Install S cut rows at row0 (inactive entries keep bu=+inf)."""
    qp = aug.qp
    dt = qp.c.dtype
    S = meta.S
    N = g.shape[-1]
    nonant_idx = np.asarray(aug.nonant_idx)
    has_eta = eta_coef is not None

    if isinstance(qp.A, EllMatrix):
        vals = qp.A.vals
        if vals.ndim == 2:
            d_slots = np.asarray(aug.d_col)[nonant_idx]
            blocks = [g * d_slots[None, :]]
            if has_eta:
                blocks.append(eta_coef[:, None])
            blocks.append(np.zeros((S, qp.A.k - N - int(has_eta))))
            vals = vals.at[row0:row0 + S].set(
                jnp.asarray(np.concatenate(blocks, -1), dt))
        else:
            d_slots = np.asarray(aug.d_col)[..., nonant_idx]  # (Sb, N)
            row_vals = g[None, :, :] * d_slots[:, None, :]
            blocks = [row_vals]
            if has_eta:
                blocks.append(np.broadcast_to(
                    eta_coef[None, :, None], row_vals.shape[:2] + (1,)))
            blocks.append(np.zeros(row_vals.shape[:2]
                                   + (qp.A.k - N - int(has_eta),)))
            vals = vals.at[:, row0:row0 + S].set(
                jnp.asarray(np.concatenate(blocks, -1), dt))
        A = dataclasses.replace(qp.A, vals=vals)
    else:
        A = qp.A
        if A.ndim == 2:
            d_slots = np.asarray(aug.d_col)[nonant_idx]
            rows = np.zeros((S, A.shape[-1]))
            rows[:, nonant_idx] = g * d_slots[None, :]
            if has_eta:
                rows[np.arange(S), meta.n_orig + np.arange(S)] = eta_coef
            A = A.at[row0:row0 + S].set(jnp.asarray(rows, dt))
        else:
            Sb = A.shape[0]
            d_slots = np.broadcast_to(
                np.asarray(aug.d_col)[..., nonant_idx],
                (Sb, len(nonant_idx)))
            rows = np.zeros((Sb, S, A.shape[-1]))
            rows[:, :, nonant_idx] = g[None] * d_slots[:, None, :]
            if has_eta:
                rows[:, np.arange(S), meta.n_orig + np.arange(S)] = \
                    eta_coef
            A = A.at[:, row0:row0 + S].set(jnp.asarray(rows, dt))

    rhs_eff = np.where(active, rhs, np.inf)
    bu = qp.bu.at[..., row0:row0 + S].set(jnp.asarray(rhs_eff, dt))
    return dataclasses.replace(
        aug, qp=dataclasses.replace(qp, A=A, bu=bu))


def write_cuts(meta: CrossScenMeta, package: dict) -> None:
    """Install one round of cuts into BOTH views (the static-shape
    analog of ref:cross_scen_extension.py:157-243 make_cuts):
      PH view:  feasibility rows only          g·x <= -const
      EF view:  feasibility rows + opt rows    g·x - eta_k <= -alpha_k
    """
    # ring buffer: when full, overwrite the OLDEST round — cuts stay
    # valid forever, but late-iteration candidates sit near the optimum
    # and dominate the early wait-and-see-era cuts
    r = meta.rounds_used % meta.max_rounds
    S = meta.S
    row0 = meta.m_orig + r * S
    infeas = package["infeas"]

    usable = package.get("usable", np.ones(S, bool))
    g = np.where(infeas[:, None], package["feas_g"], package["opt_g"])
    g = np.where(usable[:, None], g, 0.0)
    rhs = np.where(infeas, -package["feas_const"], -package["opt_alpha"])
    rhs = np.where(usable, rhs, np.inf)
    eta_coef = np.where(infeas, 0.0, -1.0)

    # the PH view holds ONLY feasibility rows; optimality-cut slopes
    # must not even occupy inactive rows there (nonzero coefficients
    # would inflate the PH subproblems' operator-norm estimate)
    g_feas = np.where((infeas & usable)[:, None], g, 0.0)
    rhs_feas = np.where(infeas & usable, rhs, np.inf)
    g_ph, _, rhs_ph = _scaled_rows(meta.aug_ph, meta, g_feas,
                                   np.zeros_like(eta_coef), rhs_feas)
    meta.aug_ph = _write_rows(meta.aug_ph, meta, row0, g_ph, None,
                              rhs_ph, active=infeas & usable)
    g_ef, eta_ef, rhs_ef = _scaled_rows(meta.aug_ef, meta, g, eta_coef,
                                        rhs)
    meta.aug_ef = _write_rows(meta.aug_ef, meta, row0, g_ef, eta_ef,
                              rhs_ef, active=usable)
    meta.is_opt[row0 - meta.m_orig:row0 - meta.m_orig + S] = \
        ~infeas & usable
    meta.rounds_used += 1


@partial(jax.jit, static_argnames=("n_orig", "windows", "opts"))
def _ef_bound_solve(aug: ScenarioBatch, owner: Array, is_opt: Array,
                    eta_lb: Array, n_orig: int, windows: int,
                    opts: pdhg.PDHGOptions, st0: pdhg.PDHGState):
    """Batched EF-objective solves on the eta view: subproblem s
    minimizes p_s f_s + sum_{k != s} p_k eta_k under its constraints +
    cuts, with its OWN eta pinned at the lower bound and its own
    optimality-cut rows deactivated (vacuous for s).  Certified dual
    values lower-bound the EF optimum; bound = max over certified
    scenarios (ref:cross_scen_extension.py:80-128 _check_bound)."""
    qp = aug.qp
    S = aug.num_scenarios
    dt = qp.c.dtype
    p = aug.p
    c_orig = qp.c[..., :n_orig] * p[:, None]
    eta_c = jnp.broadcast_to(p[None, :], (S, S)) \
        * (1.0 - jnp.eye(S, dtype=dt))
    c_ef = jnp.concatenate([c_orig, eta_c], axis=-1)

    # pin own eta: u[s, n_orig + s] = eta_lb[s]
    u = jnp.broadcast_to(qp.u, (S, qp.n))
    u = u.at[jnp.arange(S), n_orig + jnp.arange(S)].set(
        eta_lb.astype(dt))
    # deactivate own optimality-cut rows: bu[s, row] = +inf where
    # owner[row] == s and the slot holds an optimality cut
    m_orig = qp.m - owner.shape[0]
    bu_cut = jnp.broadcast_to(qp.bu[..., m_orig:],
                              (S, owner.shape[0]))
    own = (owner[None, :] == jnp.arange(S)[:, None]) & is_opt[None, :]
    bu_cut = jnp.where(own, jnp.inf, bu_cut)
    bu = jnp.concatenate(
        [jnp.broadcast_to(qp.bu[..., :m_orig], (S, m_orig)), bu_cut],
        axis=-1)

    qp_ef = dataclasses.replace(qp, c=c_ef, u=u, bu=bu)
    # the EF relaxation is feasible and bounded below by construction
    opts = dataclasses.replace(opts, detect_infeas=False)
    st = pdhg.solve_fixed(qp_ef, windows, opts, st0)
    dual = boxqp.dual_objective(qp_ef, st.x, st.y)
    _, rd, _ = boxqp.kkt_residuals(qp_ef, st.x, st.y)
    tol = jnp.maximum(opts.tol, 5.0 * jnp.finfo(dt).eps)
    ok = (rd <= 10.0 * tol) & (p > 0.0)
    bound = jnp.max(jnp.where(ok, dual, -jnp.inf))
    return bound, st


def ef_check_bound(meta: CrossScenMeta, opts: pdhg.PDHGOptions,
                   windows: int = 400,
                   st0: pdhg.PDHGState | None = None):
    """Host wrapper returning (bound_or_None, warm-startable state)."""
    aug = meta.aug_ef
    if st0 is None:
        st0 = pdhg.init_state(aug.qp, opts)
    owner = jnp.tile(jnp.arange(meta.S), meta.max_rounds)
    bound, st = _ef_bound_solve(
        aug, owner, jnp.asarray(meta.is_opt),
        jnp.asarray(meta.eta_lb), meta.n_orig, windows, opts, st0)
    b = float(bound)
    return (b if np.isfinite(b) else None), st


def eta_lower_bounds(batch: ScenarioBatch, opts: pdhg.PDHGOptions,
                     windows: int = 400, margin: float = 0.05
                     ) -> np.ndarray:
    """Valid per-scenario eta lower bounds
    (ref:cross_scen_spoke.py:120-125 set_eta_bounds + eta-lb cuts).

    Where the wait-and-see dual solve CERTIFIES (rd small), f_k over any
    x is >= that dual value minus a safety margin.  Where it does not,
    the dual value can overestimate (see boxqp.dual_objective), so fall
    back to the all-rows-dropped box relaxation
    sum_j min_{x_j in [l,u]} (c_j x_j + q_j/2 x_j^2) — always valid,
    possibly -inf (then that eta is simply unbounded below: weak but
    sound)."""
    qp = batch.qp
    st = pdhg.solve_fixed(qp, windows, opts, pdhg.init_state(qp, opts))
    dual = np.asarray(boxqp.dual_objective(qp, st.x, st.y), np.float64)
    _, rd, _ = boxqp.kkt_residuals(qp, st.x, st.y)
    tol = max(opts.tol, 5.0 * float(np.finfo(np.float32).eps))
    certified = np.asarray(rd) <= 10.0 * tol
    span = max(1.0, float(np.abs(dual).max()))

    S = batch.num_scenarios
    c = np.broadcast_to(np.asarray(qp.c, np.float64), (S, qp.n))
    q = np.broadcast_to(np.asarray(qp.q, np.float64), (S, qp.n))
    l = np.broadcast_to(np.asarray(qp.l, np.float64), (S, qp.n))
    u = np.broadcast_to(np.asarray(qp.u, np.float64), (S, qp.n))
    with np.errstate(invalid="ignore"):
        at_l = np.where(np.isfinite(l), c * l + 0.5 * q * l * l, np.inf)
        at_l = np.where(np.isfinite(l), at_l,
                        np.where((c > 0) | (q > 0), -np.inf, 0.0))
        at_u = np.where(np.isfinite(u), c * u + 0.5 * q * u * u, np.inf)
        at_u = np.where(np.isfinite(u), at_u,
                        np.where((c < 0) | (q > 0), -np.inf, 0.0))
        # interior stationary point for q > 0
        xs = np.where(q > 0, -c / np.where(q > 0, q, 1.0), 0.0)
        interior = (q > 0) & (xs > l) & (xs < u)
        at_s = np.where(interior, c * xs + 0.5 * q * xs * xs, np.inf)
    box_min = np.minimum(np.minimum(at_l, at_u), at_s).sum(axis=-1)
    lb = np.where(certified, dual - margin * span, box_min)
    # keep lb finite (f32-safe): the EF check pins each subproblem's own
    # eta at its lb, and a -inf pin degenerates the column.  -1e12 is
    # below any realistic objective, so validity (lb <= min f_k) holds.
    return np.maximum(lb, -1e12)
