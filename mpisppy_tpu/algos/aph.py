###############################################################################
# Asynchronous Projective Hedging (APH), TPU-native.
#
# The reference APH (ref:mpisppy/opt/aph.py, after Eckstein et al.,
# "Asynchronous Projective Hedging for Stochastic Programming") runs a
# worker thread plus a listener thread doing background MPI Allreduces,
# and per iteration dispatches only a FRACTION of the subproblems to the
# CPU solver (ref:opt/aph.py:717+ APH_solve_loop, dispatch_frac).  The
# projective-splitting math per iteration (Algorithm 2 of the paper;
# ref:opt/aph.py:277-443,445-658):
#
#   y_s   = W_s + rho (x_s - z)      for scenarios solved last round (Eq.25)
#   xbar  = node_avg(x),  ybar = node_avg(y)        (FirstReduce)
#   u_s   = x_s - xbar               (Eq.27),  v = ybar
#   tau   = E[ ||u||^2 + ||v||^2 / gamma ]
#   phi   = E[ (z - x)·(W - y) ]                    (SecondReduce)
#   theta = nu * phi / tau   (0 when tau<=0 or phi<=0; Steps 16-17)
#   W    += theta * u                               (Step 19)
#   z    += theta * ybar / gamma   (z = xbar at the first iteration; Step 18)
#   conv  = ||u||_p/||W||_p + ||v||_p/||z||_p       (ref:opt/aph.py:658-686)
#
# TPU design: the whole update is ONE jitted step over the scenario
# batch; node averages are the same segment reductions PH uses (XLA
# all-reduces under sharding), so the listener thread and its two named
# reductions disappear.  Fractional dispatch survives as a *mask*: every
# iteration the `ceil(dispatch_frac * S)` stalest scenarios are selected
# (the analog of the dispatch record, ref:opt/aph.py:164-168), the batch
# solve runs warm-started, and non-dispatched scenarios keep their
# previous iterates — SIMD lanes make the masked work free, while the
# algorithm sees exactly the reference's partial-dispatch semantics.
#
# y is computed AT solve time (post-solve, masked) with the same (W, z)
# the subproblem objective used; algebraically identical to the
# reference's Update_y-at-next-iteration with current values
# (ref:opt/aph.py:172-208) and to its `use_lag` variant, both of which
# evaluate y with the (W, z) that parameterized the scenario's last
# solve.
#
# Deviation (documented): the reference accumulates the u/v norms
# UNWEIGHTED for fixed-probability problems with a "the p is not true"
# comment (ref:opt/aph.py:394-404); here all four norms are consistently
# probability-weighted — the correct generalization, identical up to a
# constant factor for uniform probabilities (which cancels in theta,
# since tau and phi are then scaled equally).
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.algos.ph import PH, ph_eobjective
from mpisppy_tpu.core.batch import ScenarioBatch
from mpisppy_tpu.ops import pdhg

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class APHOptions:
    """Static APH options (ref Config group aph_args,
    ref:mpisppy/utils/config.py:396-430)."""

    default_rho: float = 1.0
    max_iterations: int = 100          # ref 'aph_max_iterations'
    conv_thresh: float = 1e-4
    gamma: float = 1.0                 # ref 'aph_gamma'
    nu: float = 1.0                    # ref 'aph_nu' (step scaling)
    dispatch_frac: float = 1.0         # ref 'aph_dispatch_frac'
    use_dynamic_gamma: bool = False    # ref _calculate_APHgamma
    subproblem_windows: int = 8
    iter0_windows: int = 400
    pdhg: pdhg.PDHGOptions = pdhg.PDHGOptions(tol=1e-6)
    display_progress: bool = False
    time_limit: float | None = None


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["solver", "W", "y", "z", "xbar", "xbar_nodes", "ybar_nodes",
                 "conv", "theta", "rho", "gamma", "last_solved", "it",
                 "pusq_prev", "pvsq_prev"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class APHState:
    solver: pdhg.PDHGState  # scaled-space subproblem iterates
    W: Array                # (S, N) duals, original space
    y: Array                # (S, N) projective-splitting auxiliary duals
    z: Array                # (S, N) per-scenario view of the z center
    xbar: Array             # (S, N) per-scenario view of node averages
    xbar_nodes: Array       # (num_nodes, N)
    ybar_nodes: Array       # (num_nodes, N)
    conv: Array             # () APH convergence metric
    theta: Array            # () last projective step length
    rho: Array              # (N,) penalty
    gamma: Array            # () APH gamma (traced: dynamic-gamma safe)
    last_solved: Array      # (S,) iteration at which s was last dispatched
    it: Array               # () int32 APH iteration counter
    pusq_prev: Array        # () previous ||u||_p^2 (dynamic gamma memory)
    pvsq_prev: Array        # () previous ||v||_p^2


def _merge_solver(mask: Array, new: pdhg.PDHGState,
                  old: pdhg.PDHGState) -> pdhg.PDHGState:
    """Keep `new` solver iterates only for dispatched scenarios.

    The per-scenario lanes of the batched PDHG state are independent, so
    a leading-axis select is exactly "those subproblems were not solved"
    (ref:opt/aph.py:717+ partial dispatch)."""
    def sel(a, b):
        if a.ndim == 0:          # global iteration counter
            return a
        m = mask.reshape(mask.shape + (1,) * (a.ndim - 1))
        return jnp.where(m, a, b)
    return jax.tree.map(sel, new, old)


@partial(jax.jit, static_argnames=("opts",))
def aph_iter0(batch: ScenarioBatch, rho: Array, opts: APHOptions):
    """Iter0: plain scenario solves (no W, no prox), z = xbar seed, y = 0,
    dual-certified trivial bound — shares semantics with PH's Iter0
    (ref:opt/aph.py:992-1067 runs PHBase.Iter0 then seeds z from xbar at
    the first work-loop pass)."""
    from mpisppy_tpu.algos.ph import iter0_solve_and_certify
    solver, trivial_bound, certified = iter0_solve_and_certify(
        batch, opts.iter0_windows, opts.pdhg)

    x_non = batch.nonants(solver.x)
    xbar, xbar_nodes = batch.node_average(x_non)
    S, N = x_non.shape
    dt = batch.qp.c.dtype
    zeros = jnp.zeros((S, N), dt)
    st = APHState(
        solver=solver, W=zeros, y=zeros, z=xbar, xbar=xbar,
        xbar_nodes=xbar_nodes, ybar_nodes=jnp.zeros_like(xbar_nodes),
        conv=jnp.asarray(jnp.inf, dt), theta=jnp.zeros((), dt),
        rho=rho, gamma=jnp.asarray(opts.gamma, dt),
        last_solved=jnp.zeros(S, jnp.int32), it=jnp.zeros((), jnp.int32),
        pusq_prev=jnp.asarray(0.0, dt), pvsq_prev=jnp.asarray(0.0, dt),
    )
    return st, trivial_bound, certified


def projective_theta(batch: ScenarioBatch, x_non: Array, xbar: Array,
                     W: Array, z_plane: Array, W_plane: Array,
                     rho: Array, nu: float = 1.0,
                     gamma: float = 1.0) -> Array:
    """APH Steps 16-17 (tau/phi/theta) against an arbitrary prox center
    — the damping the async wheel applies to its stale-plane hub step
    (algos/fused_wheel.ph_stale_step; docs/async_wheel.md).

    With z = the stale exchange plane's x̄ and y formed at the PLANE's
    era (y = W_plane + rho (x - z), Eq. 25 with the duals the plane
    carried — mirroring how aph_iterk's stored y predates the W it is
    tested against), phi = E<z - x, W - y> is the genuine separating-
    hyperplane progress of the stale direction measured against the
    CURRENT duals.  Forming y from W itself would degenerate phi to
    the always-nonnegative rho * E||x - z||^2 and disable the Step-16
    rejection entirely.  theta = nu * phi / tau contracts toward 0
    when that progress is small relative to the step norm tau — the
    regime where applying a stale update at full strength would
    overshoot — and the rejection branch (phi <= 0 -> theta = 0) fires
    for a genuinely adverse plane (torn or ancient duals pointing
    against the current iterate).  Clipped to [0, 1]: theta = 1
    recovers the undamped PH multiplier update, and the caller may
    floor it to keep duals moving near convergence."""
    u = x_non - xbar                               # Eq. 27
    y = W_plane + rho * (x_non - z_plane)          # Eq. 25, plane era
    ybar, _ = batch.node_average(y)
    pusq = batch.expectation(jnp.sum(u * u, axis=-1))
    pvsq = batch.expectation(jnp.sum(ybar * ybar, axis=-1))
    tau = pusq + pvsq / gamma
    phi = batch.expectation(
        jnp.sum((z_plane - x_non) * (W - y), axis=-1))
    dt = x_non.dtype
    theta = jnp.where((tau > 0) & (phi > 0),
                      nu * phi / jnp.maximum(tau, 1e-30),
                      jnp.zeros((), dt))
    return jnp.clip(theta, 0.0, 1.0).astype(dt)


def _dispatch_mask(batch: ScenarioBatch, st: APHState, n_dispatch: int):
    """Select the n_dispatch stalest real scenarios (the dispatch record,
    ref:opt/aph.py:164-168,756+: least-recently-solved first)."""
    S = batch.num_scenarios
    if n_dispatch >= S:
        return jnp.ones(S, bool)
    staleness = (st.it - st.last_solved).astype(jnp.float32)
    # penalize padded scenarios so they never win a slot over real ones
    staleness = jnp.where(batch.p > 0.0, staleness, -1.0)
    # deterministic tiebreak by scenario index (rotating offset so equal
    # staleness round-robins rather than always favoring low indices)
    idx = jnp.arange(S, dtype=jnp.float32)
    rot = jnp.mod(idx - st.it.astype(jnp.float32), S) / (2.0 * S)
    _, top = jax.lax.top_k(staleness + rot, n_dispatch)
    return jnp.zeros(S, bool).at[top].set(True)


@partial(jax.jit, static_argnames=("opts",))
def aph_iterk(batch: ScenarioBatch, st: APHState,
              opts: APHOptions) -> APHState:
    """One APH iteration: projective step (averages, tau/phi/theta, W/z)
    then masked partial dispatch of warm-started subproblem solves
    (ref:opt/aph.py:877-989 APH_iterk, reordered so the step uses the
    iterates produced by the previous dispatch — same dataflow)."""
    it = st.it + 1
    dt = batch.qp.c.dtype
    S = batch.num_scenarios
    N = batch.num_nonants

    # ---- FirstReduce: node averages of x and y (ref:opt/aph.py:445-530).
    # st.xbar IS node_average(nonants(st.solver.x)) by construction (both
    # iter0 and the tail of this function store the post-dispatch
    # average), so x's reduction needs no recompute here.
    x_non = batch.nonants(st.solver.x)
    xbar, xbar_nodes = st.xbar, st.xbar_nodes
    ybar, ybar_nodes = batch.node_average(st.y)

    u = x_non - xbar                       # Eq. 27
    v = ybar                               # per-scenario view of node ybar
    pusq = batch.expectation(jnp.sum(u * u, axis=-1))
    pvsq = batch.expectation(jnp.sum(v * v, axis=-1))

    # ---- dynamic gamma (ref:opt/aph.py:228-275), guarded exactly like
    # the reference: only after iteration 3, only when both norms and
    # both decrease ratios are positive.
    if opts.use_dynamic_gamma:
        u_term = (st.pusq_prev - pusq) / jnp.maximum(pusq, 1e-30)
        v_term = (st.pvsq_prev - pvsq) / jnp.maximum(pvsq, 1e-30)
        ok = (it > 3) & (pusq > 0) & (pvsq > 0) & (u_term > 0) & (v_term > 0)
        gamma = jnp.where(ok, v_term / jnp.maximum(u_term, 1e-30), st.gamma)
        pusq_prev = jnp.where(ok | (it <= 3), pusq, st.pusq_prev)
        pvsq_prev = jnp.where(ok | (it <= 3), pvsq, st.pvsq_prev)
    else:
        gamma = st.gamma
        pusq_prev, pvsq_prev = pusq, pvsq

    # ---- SecondReduce: tau and phi (ref:opt/aph.py:330-443)
    tau = pusq + pvsq / gamma
    phi = batch.expectation(jnp.sum((st.z - x_non) * (st.W - st.y), axis=-1))

    # ---- Steps 16-19 (ref:opt/aph.py:579-658)
    theta = jnp.where((tau > 0) & (phi > 0),
                      opts.nu * phi / jnp.maximum(tau, 1e-30),
                      jnp.zeros((), dt))
    W = st.W + theta * u
    z = jnp.where(it == 1, xbar, st.z + theta * ybar / gamma)

    pwsq = batch.expectation(jnp.sum(W * W, axis=-1))
    pzsq = batch.expectation(jnp.sum(z * z, axis=-1))
    pun, pwn = jnp.sqrt(pusq), jnp.sqrt(pwsq)
    pvn, pzn = jnp.sqrt(pvsq), jnp.sqrt(pzsq)
    conv = jnp.where((pwn > 0) & (pzn > 0),
                     pun / jnp.maximum(pwn, 1e-30)
                     + pvn / jnp.maximum(pzn, 1e-30),
                     jnp.asarray(jnp.inf, dt))

    # ---- partial dispatch + solve (ref:opt/aph.py:717+; iteration 1
    # forces full dispatch "to get a decent w for everyone",
    # ref:opt/aph.py:955-958)
    n_dispatch = max(1, int(np.ceil(opts.dispatch_frac * batch.num_real)))
    mask = _dispatch_mask(batch, dataclasses.replace(st, it=it), n_dispatch)
    mask = mask | (it == 1)

    # subproblem objective: f_s(x) + W·x + rho/2 (x - z)^2  — prox is
    # around z, not xbar (ref:opt/aph.py:1040-1062)
    lin = W - st.rho * z
    quad = jnp.broadcast_to(st.rho, (S, N))
    qp_eff = batch.with_nonant_linear_quad(lin, quad)
    solved = pdhg.solve_fixed(qp_eff, opts.subproblem_windows, opts.pdhg,
                              st.solver)
    solver = _merge_solver(mask, solved, st.solver)

    # y at solve time with the same (W, z) the objective used (Eq. 25)
    x_new = batch.nonants(solver.x)
    y = jnp.where(mask[:, None], W + st.rho * (x_new - z), st.y)
    last_solved = jnp.where(mask, it, st.last_solved)

    # store the POST-dispatch average so the returned state is
    # self-consistent (hub snapshots, convergers, nonant_values all see
    # the same generation as solver.x); the next iteration reuses it.
    xbar_new, xbar_nodes_new = batch.node_average(x_new)

    return dataclasses.replace(
        st, solver=solver, W=W, y=y, z=z, xbar=xbar_new,
        xbar_nodes=xbar_nodes_new, ybar_nodes=ybar_nodes, conv=conv,
        theta=theta, gamma=gamma, last_solved=last_solved, it=it,
        pusq_prev=pusq_prev, pvsq_prev=pvsq_prev)


aph_eobjective = ph_eobjective  # same reduction; any state with .solver


class APH(PH):
    """Host-side APH driver (ref:mpisppy/opt/aph.py:992-1161 APH_main).

    Subclasses the PH driver — all extension/converger/spcomm plumbing,
    Eobjective, and solution access are shared; only the jitted step
    functions differ.  `APH_main() -> (conv, Eobj, trivial_bound)`.
    The reference warns its conv and Eobj "CANNOT BE EASILY INTERPRETED"
    (Eobj includes the prox term there); here Eobj is the clean
    E[f_s(x_s)] at the final iterates, which IS interpretable.
    """

    _label = "APH"

    def __init__(self, options: APHOptions, batch: ScenarioBatch, **kw):
        super().__init__(options, batch, **kw)
        self.state: APHState | None = None

    def _iter0_impl(self):
        return aph_iter0(self.batch, self.rho, self.options)

    def _iterk_impl(self):
        return aph_iterk(self.batch, self.state, self.options)

    def _iter_msg(self, k: int, conv: float) -> str:
        return (f"APH iter {k}: conv = {conv:.3e} "
                f"theta = {float(self.state.theta):.3e}")

    def APH_main(self):
        """Returns (conv, Eobj, trivial_bound) (ref:opt/aph.py:992+)."""
        return self.ph_main()
