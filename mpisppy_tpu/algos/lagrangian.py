###############################################################################
# Lagrangian outer bounds from the scenario batch.
#
# The reference computes outer (lower, for min) bounds in separate spoke
# processes that re-solve every scenario with the hub's W fixed in the
# objective and no prox term, then Allreduce the expectation
# (ref:mpisppy/cylinders/lagrangian_bounder.py:11-51,
# ref:mpisppy/cylinders/subgradient_bounder.py:12-54).  TPU-native, the
# "spoke" is just another batched solve over the SAME HBM-resident
# scenario tensors:
#
#     L(W) = E_s [ min_x  f_s(x) + W_s . x_non ]   with  E_node[W] = 0
#
# is one `solve` call on a qp whose c has W added on nonant slots.  The
# bound is certified from the DUAL side: each subproblem's Fenchel dual
# value at its final iterates is the bound contribution, and scenarios
# whose dual residual has not cleared tolerance are flagged so the caller
# can treat the bound as heuristic rather than certified
# (the analog of the reference trusting Gurobi's bestbound,
# ref:mpisppy/spopt.py:413-436 Ebound over outer bounds).
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from mpisppy_tpu.core.batch import ScenarioBatch
from mpisppy_tpu.ops import boxqp, pdhg

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["bound", "per_scenario", "dual_resid", "certified", "solver"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class LagrangianResult:
    bound: Array        # () E_s[dual value + W·x handled inside]
    per_scenario: Array  # (S,) per-scenario dual values
    dual_resid: Array   # (S,) relative dual residuals at exit
    certified: Array    # () bool: all real scenarios cleared tolerance
    solver: pdhg.PDHGState


def _lagrangian_qp(batch: ScenarioBatch, W: Array) -> boxqp.BoxQP:
    """Scenario objectives + W·x_non (no prox) —
    ref:mpisppy/cylinders/lagrangian_bounder.py:13-19 (PH_Prep with
    attach_prox=False, W reenabled)."""
    zeros = jnp.zeros_like(W)
    return batch.with_nonant_linear_quad(W, zeros)


def lagrangian_bound(batch: ScenarioBatch, W: Array,
                     opts: pdhg.PDHGOptions = pdhg.PDHGOptions(),
                     solver: pdhg.PDHGState | None = None) -> LagrangianResult:
    """One Lagrangian bound evaluation L(W); valid outer bound when the
    per-node probability-weighted mean of W is ~0 (PH invariant,
    ref:mpisppy/phbase.py:114-179 Compute_Wbar check).

    Budgets within dispatch_cap run as ONE jitted program (async — the
    classic spokes' overlap contract depends on update() not blocking);
    larger budgets — e.g. the certification pipeline's 100k-iteration
    evaluations — take a host-level path where pdhg.solve's
    auto-chunking splits the work into worker-safe dispatches (that
    path is inherently synchronous).
    """
    if not pdhg.will_chunk(opts):
        return _lagrangian_bound_jit(batch, W, opts, solver)
    return _lagrangian_bound_impl(batch, W, opts, solver)


def _lagrangian_bound_impl(batch: ScenarioBatch, W: Array,
                           opts: pdhg.PDHGOptions,
                           solver: pdhg.PDHGState | None) -> LagrangianResult:
    qp = _lagrangian_qp(batch, W)
    st = pdhg.init_state(qp, opts) if solver is None else solver
    st = pdhg.solve(qp, opts, st)
    return _lagrangian_epilogue(batch, qp, st, opts)


_lagrangian_bound_jit = partial(jax.jit, static_argnames=("opts",))(
    _lagrangian_bound_impl)


@partial(jax.jit, static_argnames=("opts",))
def _lagrangian_epilogue(batch: ScenarioBatch, qp: boxqp.BoxQP,
                         st: pdhg.PDHGState,
                         opts: pdhg.PDHGOptions) -> LagrangianResult:
    # Dual value of each subproblem (contains the W·x term implicitly:
    # the qp objective IS f_s + W·x_non in scaled space).
    dual = boxqp.dual_objective(qp, st.x, st.y)
    _, rd, _ = boxqp.kkt_residuals(qp, st.x, st.y)
    tol = jnp.maximum(opts.tol, 5.0 * jnp.finfo(st.x.dtype).eps)
    bound = batch.expectation(dual)
    real = batch.p > 0.0
    certified = jnp.all(jnp.where(real, rd <= 10.0 * tol, True))
    return LagrangianResult(bound=bound, per_scenario=dual, dual_resid=rd,
                            certified=certified, solver=st)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["W", "xbar", "solver", "bound", "best_bound", "certified"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class SubgradientState:
    W: Array
    xbar: Array
    solver: pdhg.PDHGState
    bound: Array
    best_bound: Array   # max over CERTIFIED bounds only
    certified: Array    # () bool: last bound's dual residuals cleared tol


@partial(jax.jit, static_argnames=("opts", "n_windows"))
def subgradient_step(batch: ScenarioBatch, st: SubgradientState, rho: Array,
                     opts: pdhg.PDHGOptions, n_windows: int = 8
                     ) -> SubgradientState:
    """One subgradient iteration: solve with current W (no prox), take the
    nonanticipativity subgradient W += rho*(x - xbar), record the bound
    (ref:mpisppy/cylinders/subgradient_bounder.py:12-54 =
    Compute_Xbar + Update_W + lagrangian bound, fused).

    A truncated (fixed-budget) solve can leave the dual iterate
    infeasible, in which case dual_objective OVERESTIMATES L(W) — such
    bounds are not valid and must not enter best_bound; they are gated by
    the same dual-residual certificate as lagrangian_bound."""
    qp = _lagrangian_qp(batch, st.W)
    solver = pdhg.solve_fixed(qp, n_windows, opts, st.solver)
    dual = boxqp.dual_objective(qp, solver.x, solver.y)
    _, rd, _ = boxqp.kkt_residuals(qp, solver.x, solver.y)
    tol = jnp.maximum(opts.tol, 5.0 * jnp.finfo(solver.x.dtype).eps)
    real = batch.p > 0.0
    certified = jnp.all(jnp.where(real, rd <= 10.0 * tol, True))
    bound = batch.expectation(dual)
    x_non = batch.nonants(solver.x)
    xbar, _ = batch.node_average(x_non)
    W = st.W + rho * (x_non - xbar)
    best = jnp.where(certified, jnp.maximum(st.best_bound, bound),
                     st.best_bound)
    return SubgradientState(W=W, xbar=xbar, solver=solver, bound=bound,
                            best_bound=best, certified=certified)


def subgradient_init(batch: ScenarioBatch,
                     opts: pdhg.PDHGOptions = pdhg.PDHGOptions(),
                     W: Array | None = None) -> SubgradientState:
    S, N = batch.num_scenarios, batch.num_nonants
    dt = batch.qp.c.dtype
    if W is None:
        W = jnp.zeros((S, N), dt)
    qp = _lagrangian_qp(batch, W)
    return SubgradientState(
        W=W,
        xbar=jnp.zeros((S, N), dt),
        solver=pdhg.init_state(qp, opts),
        bound=jnp.asarray(-jnp.inf, dt),
        best_bound=jnp.asarray(-jnp.inf, dt),
        certified=jnp.asarray(False),
    )


@partial(jax.jit, static_argnames=())
def nonant_reduced_costs(batch: ScenarioBatch, W: Array,
                         solver: pdhg.PDHGState) -> Array:
    """(S, N) ORIGINAL-space reduced costs of the nonant columns at a
    Lagrangian solve's (x, y) — the batched analog of the reference's
    per-scenario solver rc suffix extraction
    (ref:mpisppy/cylinders/reduced_costs_spoke.py:108-171).

    rc_orig = (c + q x + A'y)[nonant] / d_non: the scaled-space gradient
    maps to original units through the column scaling."""
    qp = _lagrangian_qp(batch, W)
    rc = qp.c + qp.q * solver.x + qp.rmatvec(solver.y)
    return rc[..., batch.nonant_idx] / batch.d_non
