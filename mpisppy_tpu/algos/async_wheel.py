###############################################################################
# Asynchronous wheel driver (ISSUE 11 tentpole; ROADMAP item 4;
# docs/async_wheel.md).
#
# The synchronous fused wheel serializes harvest -> validate ->
# plane-write -> device step every sync: the device idles while the
# host completes the exchange and vice versa (2.41x over bare PH,
# BENCH_DETAIL.json wheel_overhead).  APH (Eckstein et al., transcribed
# in algos/aph.py) names the cure — run projections and bounds without
# a barrier against a stale-but-bounded plane — and the
# Proximal-Proximal-Gradient line (PAPERS.md, arXiv:1708.06908)
# supplies the convergence frame for prox iterations against a stale
# W/x̄ center.
#
# Mechanics (staleness s >= 1):
#
#   * a DOUBLE-BUFFERED exchange plane (two ExchangePlane slots of
#     device refs): the device step of iteration k reads slot k mod 2,
#     the host writes slot (k+1) mod 2 with generation k+1-s (a delay
#     line of device refs — a plane write is a pointer swap, never a
#     transfer);
#   * the hub PH step proxes around the PLANE x̄ with the multiplier
#     update theta-damped by the APH projective step length
#     (fused_wheel.ph_stale_step) so stale updates stay convergent;
#   * the spoke planes (Lagrangian / x̂ / slam / shuffle) evaluate AT
#     the plane — L(W) is a certified outer bound at ANY W, and every
#     candidate evaluation keeps its feasibility + comp-tightness
#     gates, so staleness can delay bounds but never invalidate them;
#   * plane dispatches ride fire-and-forget PlaneTickets through the
#     dispatch scheduler (PR-8 deadline semantics: a wedged exchange
#     becomes a typed SolveFailed / a watchdog trip, never a hang);
#   * the host reads results pipelined (the existing depth-2 scalar
#     cache plus a one-slot theta pipeline), so it never blocks on the
#     in-flight step — host exchange work overlaps device iterations.
#
# staleness = 0 degrades to the synchronous FusedPH path UNTOUCHED
# (same jitted programs, same host loop), so trajectories are
# bit-identical by construction — tests/test_async_wheel.py asserts it
# on bounds, trace events and checkpoint contents.
###############################################################################
from __future__ import annotations

import dataclasses

import numpy as np

from mpisppy_tpu.algos import fused_wheel as fw
from mpisppy_tpu.algos import ph as ph_mod


@dataclasses.dataclass(frozen=True)
class AsyncWheelOptions:
    """Host-side async-wheel knobs (CLI: --async-staleness).

    staleness: hard bound on how many iterations the exchange plane
    may lag the device step (0 = synchronous; fault injection may
    exceed it deliberately — validity never depends on it).  nu/gamma
    feed the APH theta formula; theta_floor keeps the damped multiplier
    update flowing near convergence (docs/async_wheel.md).
    exchange_deadline_s bounds how long the exchange may block on any
    plane ticket before a typed SolveFailed surfaces."""

    staleness: int = 1
    nu: float = 1.0
    gamma: float = 1.0
    theta_floor: float = 0.05
    exchange_deadline_s: float | None = None


class AsyncFusedPH(fw.FusedPH):
    """FusedPH whose iteration runs against the double-buffered stale
    exchange plane.  Pair with cylinders.hub.AsyncPHHub (which emits
    the plane-write/overlap telemetry and runs the host-complete half
    of the exchange on the stale side of the pipeline)."""

    def __init__(self, options, batch, wheel_options=None,
                 async_options: AsyncWheelOptions | None = None, **kw):
        super().__init__(options, batch, wheel_options, **kw)
        self.async_options = async_options or AsyncWheelOptions()
        # double buffer of ExchangePlane device-ref slots; a "write" is
        # a host-side pointer swap (arrays are immutable), routed
        # through the fault plan's torn/dropped-write seams.  Touched
        # only on the hub driver thread — the background checkpoint
        # writer never reads the ring.
        self._plane_slots: list = [None, None]
        self._plane_slot_gen: list = [0, 0]  # generation each slot holds
        self._plane_delay: list = []   # generation delay line, len <= s
        self._theta_inflight = None    # () device scalar, 1-deep pipeline
        self.last_theta: float | None = None
        self.plane_events: list[dict] = []   # drained by AsyncPHHub
        self._exchange_tickets: list = []    # THIS iteration's tickets
        self._tickets_due: list = []         # previous iteration's

    # -- plane bookkeeping ------------------------------------------------
    def take_plane_events(self) -> list[dict]:
        out, self.plane_events = self.plane_events, []
        return out

    def _write_plane(self, phst: ph_mod.PHState):
        """Append generation self._iter to the delay line and write the
        due generation into slot (iter+1) mod 2 — the slot the NEXT
        iteration's device step reads.  The fault plan's async-exchange
        seams (drop / torn swap) intercept here; the recorded event
        carries the generation the slot ACTUALLY holds afterwards, so
        a dropped/torn write shows its observed staleness exceeding
        the bound (exactly what the fault exists to probe)."""
        s = max(1, int(self.async_options.staleness))
        self._plane_delay.append((self._iter, fw.plane_of(phst)))
        while len(self._plane_delay) > s:
            self._plane_delay.pop(0)
        gen, plane = self._plane_delay[0]
        slot = (self._iter + 1) % 2
        plan = self.options_fault_plan()
        old = self._plane_slots[slot]
        if plan is not None and old is not None:
            filtered = plan.filter_plane_write(self._iter, plane, old)
            if filtered is old:
                # dropped write: the slot keeps its previous generation
                gen = self._plane_slot_gen[slot]
            elif filtered is not plane:
                # torn swap: the stalest mixed-in component governs
                # what the device actually reads
                gen = min(self._plane_slot_gen[slot], gen)
            plane = filtered
        self._plane_slots[slot] = plane
        self._plane_slot_gen[slot] = gen
        self.plane_events.append({
            "slot": slot, "generation": gen,
            "staleness": self._iter + 1 - gen})

    def options_fault_plan(self):
        """The run's FaultPlan, if the hub armed one (the hub owns the
        options dict; the driver only reads the seam)."""
        spcomm = getattr(self, "spcomm", None)
        if spcomm is None:
            return None
        return spcomm.options.get("fault_plan")

    # -- iteration --------------------------------------------------------
    def _iter0_impl(self):
        phst, tb, cert = super()._iter0_impl()
        if int(self.async_options.staleness) > 0:
            # seed both slots with the iter0 generation so the first
            # iterk reads a valid plane (staleness 1 at iteration 1)
            plane = fw.plane_of(self.wstate.ph)
            self._plane_slots = [plane, plane]
            self._plane_slot_gen = [0, 0]
            self._plane_delay = [(0, plane)]
        return phst, tb, cert

    def _iterk_impl(self):
        if int(self.async_options.staleness) <= 0:
            # synchronous degrade: the untouched FusedPH path —
            # bit-identical trajectories (tested)
            return super()._iterk_impl()
        return self._iterk_async()

    def _plane_dispatch(self, label, fn, *args):
        """One fire-and-forget plane dispatch: through the scheduler's
        PlaneTicket when one is configured (PR-8 deadline semantics),
        else a direct async XLA dispatch."""
        from mpisppy_tpu import dispatch as _dispatch
        sched = _dispatch.get_scheduler(create=False)
        if sched is None:
            return fn(*args)
        ticket = sched.submit_plane(
            fn, *args, label=label,
            deadline_s=self.async_options.exchange_deadline_s)
        self._exchange_tickets.append(ticket)
        return ticket.value

    def result_exchange(self):
        """Bounded settle of the PREVIOUS iteration's plane tickets —
        the host-complete half's 'observe a result or a typed
        SolveFailed' point (dispatch/scheduler.PlaneTicket).  The
        current iteration's tickets stay in flight (settling them here
        would re-introduce the host<->device barrier this wheel
        removes); they rotate into the due list at the next iterk and
        settle one sync later, after a full iteration to land."""
        tickets, self._tickets_due = self._tickets_due, []
        self._settle(tickets)

    def _settle(self, tickets):
        """Settle EVERY ticket — one wedged dispatch must not leave its
        siblings unsettled/uncounted (each gets its result-or-typed-
        SolveFailed observation); the first failure re-raises after the
        sweep."""
        deadline = self.async_options.exchange_deadline_s
        first_exc = None
        for t in tickets:
            try:
                t.result(timeout=deadline)
            except Exception as e:
                if first_exc is None:
                    first_exc = e
        if first_exc is not None:
            raise first_exc

    def _iterk_async(self):
        aopts = self.async_options
        batch = self.batch
        # rotate: LAST iteration's tickets become settleable at this
        # sync's host-complete half (result_exchange)
        self._tickets_due.extend(self._exchange_tickets)
        self._exchange_tickets = []
        # self-defense for a mispaired hub (public API: this driver
        # under a plain PHHub never gets result_exchange /
        # take_plane_events calls): a properly paired hub drains both
        # every sync, so growth past a few iterations' worth means
        # nobody is draining — settle/trim here rather than pin every
        # ticket's device arrays for the whole run
        if len(self._tickets_due) > 32:
            due, self._tickets_due = self._tickets_due, []
            self._settle(due)
        if len(self.plane_events) > 32:
            del self.plane_events[:-8]
        sid, spoke_iter = self._draw_spoke_cycle()
        plane = self._plane_slots[self._iter % 2]
        if plane is not None \
                and plane.W.shape[0] != self.wstate.ph.W.shape[0]:
            # reshard-safe restore (ISSUE 17): an elastic re-shard
            # restored a re-partitioned state whose scenario axis no
            # longer matches the seeded slots — planes of the old
            # layout are unreadable by the new device programs, so
            # drop both slots and fall into the re-seed path below
            plane = None
            self._plane_slots = [None, None]
        if plane is None:
            # restored from a checkpoint: load_checkpoint skips
            # _iter0_impl, so re-seed both slots (and the delay line's
            # generation stamp) from the restored state — the first
            # resumed write then reports staleness 1, like iteration 1
            plane = fw.plane_of(self.wstate.ph)
            self._plane_slots = [plane, plane]
            self._plane_slot_gen = [self._iter - 1, self._iter - 1]
            self._plane_delay = [(self._iter - 1, plane)]
        # device-issue half: the theta-damped hub step against the
        # stale plane, then every enabled spoke plane AT the plane —
        # none of their inputs depend on this step's output, so the
        # dispatches are data-independent of it
        phst, theta = fw.ph_stale_step(
            batch, self.state, plane, ph_mod.kernel_opts(self.options),
            aopts.nu, aopts.gamma, aopts.theta_floor)
        out = dataclasses.replace(self.wstate, ph=phst)
        if spoke_iter:
            out = self._dispatch_spoke_planes(
                out, plane.W, plane.xbar_nodes, plane.x, sid,
                dispatch=self._plane_dispatch)
        self.wstate = dataclasses.replace(
            out, scalars=fw._pack_scalars_jit(out))
        self._write_plane(phst)
        # pipelined host reads: the PREVIOUS iteration's packed scalars
        # and theta — the host never blocks on the in-flight step
        prev_theta, self._theta_inflight = self._theta_inflight, theta
        if prev_theta is not None:
            self.last_theta = float(np.asarray(prev_theta))
        self._cache_scalars(pipelined=True)
        if spoke_iter:
            self._observe_progress()
        return self.wstate.ph

    def flush_scalars(self):
        super().flush_scalars()
        # finalize path: settle every outstanding plane ticket so the
        # last iteration's dispatches keep the typed-failure contract
        due, self._tickets_due = self._tickets_due, []
        cur, self._exchange_tickets = self._exchange_tickets, []
        self._settle(due + cur)
        if self._theta_inflight is not None:
            self.last_theta = float(np.asarray(self._theta_inflight))
