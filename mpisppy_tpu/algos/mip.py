###############################################################################
# Exact integer optimization over the scenario batch.
#
# The reference certifies integer solutions by delegating every scenario
# subproblem to Gurobi/CPLEX (ref:mpisppy/spopt.py:99-247,884) and gets
# its MIP gap from the hub's outer/inner bound bookkeeping
# (ref:mpisppy/cylinders/hub.py:82-166).  This module is the TPU-native
# equivalent, built on ops/bnb.py's batched branch-and-bound:
#
#   * lagrangian_mip_bound — a certified OUTER bound for the true MIP:
#       L(W) = E_s[ min over the INTEGER feasible set of f_s + W.x_non ]
#     with E_node[W] = 0 (PH's invariant).  Each scenario's inner min is
#     its own MIP; the batched B&B advances all of them in lockstep and
#     its per-scenario outer bounds make E[outer_s] <= L(W) <= z_MIP
#     valid even when the round budget truncates the search.
#   * evaluate_mip — a certified INNER bound: fix an integral first
#     stage and solve every scenario's integer recourse exactly
#     (the reference's Xhat_Eval with MIP subproblems,
#     ref:mpisppy/utils/xhat_eval.py:254-340).
#   * ef_mip — branch-and-bound on the assembled extensive form (one
#     "scenario" of size S*n): the oracle that replaces handing
#     sputils.create_EF to Gurobi (ref:mpisppy/opt/ef.py:75-104).
#   * certified_mip_gap — the driver: LP-relaxed PH for (W, xbar),
#     candidate first stages from the xhat plane, then the two bounds
#     above; reports a TRUE MIP gap, which the LP-relax framework of
#     rounds 1-2 could not produce.
###############################################################################
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from mpisppy_tpu import dispatch as _dispatch
from mpisppy_tpu.core.batch import ScenarioBatch
from mpisppy_tpu.telemetry import console as _console
from mpisppy_tpu.ops import bnb, pdhg
from mpisppy_tpu.ops.bnb import BnBOptions

# Every solve_mip in this module goes through the dispatch scheduler
# (docs/dispatch.md): batch shapes are padded up the bucket ladder so
# the oracle loops below cannot recompile-storm the device tunnel, and
# concurrent callers (spokes, threaded drivers, the decomposition-B&B
# node fanout) coalesce into megabatch dispatches bounded by the
# in-flight cap.  Results match the direct ops.bnb path within
# certified-bound tolerances, and every bound keeps its certificate
# (see the padding contract in dispatch/buckets.py).
#
# Failure semantics (docs/dispatch.md): under a configured dispatch
# fault domain a quarantined solve raises dispatch.SolveFailed instead
# of hanging.  decomposition_bnb absorbs per-node failures (the parent
# bound stays a certified stand-in); the one-shot oracles
# (lagrangian_mip_bound, evaluate_mip*, ef_mip) propagate SolveFailed
# to their caller — a typed, catchable outcome, never a wedge.

Array = jnp.ndarray


def _aggregate_inner(per_scenario, feas_s, p):
    """(value, feasible): the all-real-scenarios-feasible gate and
    p-weighted expectation shared by evaluate_mip and its polished
    variant (one place for the padded-scenario and inf-sentinel
    rules)."""
    real = p > 0.0
    feas = bool(np.all(np.where(real, np.asarray(feas_s), True)))
    inner_s = np.asarray(per_scenario)
    value = float(np.sum(np.where(real, p * inner_s, 0.0))) if feas \
        else float("inf")
    return value, feas, inner_s


def _int_cols(batch: ScenarioBatch) -> np.ndarray:
    cols = np.nonzero(np.asarray(batch.integer_full))[0]
    if cols.size == 0:
        raise ValueError("problem has no integer columns; use the LP path")
    return cols.astype(np.int32)


def lagrangian_mip_bound(batch: ScenarioBatch, W: Array,
                         opts: BnBOptions = BnBOptions()) -> dict:
    """Certified MIP outer bound at multiplier W (valid when the
    per-node probability-weighted mean of W is 0, the PH invariant —
    ref:mpisppy/phbase.py:114-179).  Unlike algos/lagrangian.py this
    solves each scenario's Lagrangian subproblem AS A MIP, so the bound
    is the (stronger) Lagrangian dual of the integer problem — the bound
    the reference gets from exact Gurobi subproblem solves
    (ref:mpisppy/cylinders/lagrangian_bounder.py:21-44)."""
    zeros = jnp.zeros_like(W)
    qp = batch.with_nonant_linear_quad(W, zeros)
    res = _dispatch.solve_mip(qp, batch.d_col, _int_cols(batch), opts)
    p = np.asarray(batch.p)
    outer_s = np.asarray(res.outer)
    # padded scenarios (p=0) may carry -inf outers; mask before weighing
    bound = float(np.sum(np.where(p > 0.0, p * outer_s, 0.0)))
    return {
        "bound": bound,
        "per_scenario": outer_s,
        "solved": np.asarray(res.gap) <= opts.gap_tol,
        "result": res,
    }


def _polish_swap(opts: BnBOptions) -> BnBOptions:
    """Resolve swap_rounds for a polish context: 0 (auto) promotes to
    bnb.POLISH_SWAP_ROUNDS; an explicit caller value — positive (tuned
    budget) or negative (force off) — is honored verbatim."""
    if opts.swap_rounds != 0:
        return opts
    return dataclasses.replace(opts, swap_rounds=bnb.POLISH_SWAP_ROUNDS)


def evaluate_mip(batch: ScenarioBatch, xhat: Array,
                 opts: BnBOptions = BnBOptions()) -> dict:
    """Certified MIP inner bound: E[f(xhat)] with INTEGER recourse.

    xhat ((N,) root-only or (num_nodes, N)) is rounded on integer slots
    first; each scenario's recourse MIP is then solved by the batched
    B&B.  `value` is +inf unless every real scenario found an
    integer-feasible recourse (matching the reference's all-feasible
    gate, ref:mpisppy/utils/xhat_eval.py:254-340).

    Candidate evaluation is a POLISH context (the value becomes a
    published certified inner bound), so the dual-guided SOS1 swap
    repair is enabled here explicitly (bnb.POLISH_SWAP_ROUNDS) — the
    base options default it to 0 = auto to keep the hot Lagrangian-
    oracle loops (lagrangian_mip_bound, mip_dual_bundle) lean; an
    explicit caller value (positive or negative) is honored verbatim
    (see BnBOptions.swap_rounds)."""
    opts = _polish_swap(opts)
    xhat = jnp.asarray(xhat)
    xhat = jnp.where(batch.integer_slot, jnp.round(xhat), xhat)
    qp = batch.with_fixed_nonants(xhat)
    res = _dispatch.solve_mip(qp, batch.d_col, _int_cols(batch), opts)
    p = np.asarray(batch.p)
    real = p > 0.0
    value, feas, inner_s = _aggregate_inner(res.inner, res.feasible, p)
    # the recourse B&B's outer bounds bracket the true E[f(xhat)]
    lower = float(np.sum(np.where(real, p * np.asarray(res.outer), 0.0)))
    return {
        "value": value,
        "value_lower": lower,
        "per_scenario": inner_s,
        "feasible": feas,
        "xhat": np.asarray(xhat),
        "result": res,
    }


def evaluate_mip_polished(batch: ScenarioBatch, xhat: Array,
                          opts: BnBOptions = BnBOptions(),
                          multistart: int = 24, lns_rounds: int = 60,
                          base: dict | None = None,
                          verbose: bool = False) -> dict:
    """evaluate_mip plus the heavy per-scenario incumbent polish for
    FINAL-candidate certification: jitter-diversified multistart dives
    (ops/bnb.dive_multistart) merged with the B&B incumbents, then
    large-neighborhood repair (ops/bnb.lns_repair).  Measured on
    sslp_15_45_5 at the published-optimal first stage: plain B&B
    incumbents E=-257.6, +swap/LNS -259.4, diversified-LNS merge
    reaches the per-scenario optima on 4 of 5 scenarios (scipy-MILP
    ground truth -262.4).

    The swap repair rides the internal evaluate_mip (a polish context,
    see its docstring); multistart/LNS are this function's own adds."""
    # polish context: the dual-guided SOS1 swap repair is enabled
    # explicitly (bnb.POLISH_SWAP_ROUNDS) for this function's own bnb
    # calls too (dive_multistart/lns_repair), not just the internal
    # evaluate_mip — callers passing `base` would otherwise polish with
    # the lean swap_rounds=0 defaults
    opts = _polish_swap(opts)
    # callers holding a fresh evaluate_mip dict for the SAME xhat can
    # pass it as `base` and skip the (expensive) internal re-solve
    if base is None:
        base = evaluate_mip(batch, xhat, opts)
    res = base["result"]
    inc = jnp.asarray(res.inner)
    x_inc = jnp.asarray(res.x)
    feas_s = jnp.asarray(res.feasible)
    qp = batch.with_fixed_nonants(jnp.asarray(base["xhat"]))
    int_cols = jnp.asarray(_int_cols(batch))
    sos1 = bnb.detect_sos1_groups(qp, batch.d_col, int_cols)
    if multistart > 0:
        ms = bnb.dive_multistart(qp, batch.d_col, int_cols, opts,
                                 K=multistart, sos1=sos1)
        inc, x_inc, feas_s = bnb.merge_incumbents(inc, x_inc, feas_s,
                                                  *ms)
        if verbose:
            _console.log(f"[polish] multistart merge: {np.asarray(inc)}")
    if lns_rounds > 0:
        rep = bnb.lns_repair(qp, batch.d_col, int_cols, x_inc, inc,
                             feas_s, opts, rounds=lns_rounds,
                             destroy_frac=0.35, sos1=sos1,
                             verbose=verbose)
        if rep is not None:
            inc, x_inc, feas_s = bnb.merge_incumbents(inc, x_inc,
                                                      feas_s, *rep)
    value, feas, inner_s = _aggregate_inner(inc, feas_s,
                                            np.asarray(batch.p))
    out = dict(base)
    out.update({"value": value, "per_scenario": inner_s,
                "feasible": feas,
                # the POLISHED per-scenario solutions achieving
                # per_scenario/value (base["result"].x is pre-polish)
                "x": np.asarray(x_inc)})
    return out


def evaluate_mip_many(batch: ScenarioBatch, xhats,
                      opts: BnBOptions = BnBOptions()) -> list[dict]:
    """Certified MIP inner bounds for K candidate first stages in ONE
    batched B&B of K*S subproblems (the TPU answer to the reference's
    shuffle looper trying candidates sequentially across ranks,
    ref:mpisppy/cylinders/xhatshufflelooper_bounder.py:23-157).
    Returns one evaluate_mip-style dict per candidate.

    Like its siblings this is a POLISH context (the values become
    published certified inner bounds), so swap_rounds=0 (auto)
    promotes to bnb.POLISH_SWAP_ROUNDS — pass a negative swap_rounds
    to force the repair off for cheap candidate screening."""
    opts = _polish_swap(opts)
    K = len(xhats)
    if K == 0:
        return []
    S = batch.num_scenarios
    n = batch.qp.c.shape[-1]
    qps = []
    for xh in xhats:
        xh = jnp.asarray(xh)
        xh = jnp.where(batch.integer_slot, jnp.round(xh), xh)
        qps.append(batch.with_fixed_nonants(xh))

    def tileS(x, batched_ndim):
        if hasattr(x, "vals"):  # EllMatrix
            return dataclasses.replace(x, vals=tileS(x.vals, batched_ndim))
        if getattr(x, "ndim", 0) != batched_ndim:
            return x  # shared: broadcasts across the K*S batch
        return jnp.tile(x, (K,) + (1,) * (batched_ndim - 1))

    qp0 = batch.qp
    qp = dataclasses.replace(
        qp0,
        c=tileS(qp0.c, 2), q=tileS(qp0.q, 2), A=tileS(qp0.A, 3),
        bl=tileS(qp0.bl, 2), bu=tileS(qp0.bu, 2),
        l=jnp.concatenate([q.l for q in qps], axis=0),
        u=jnp.concatenate([q.u for q in qps], axis=0))
    d_col = tileS(batch.d_col, 2)
    res = _dispatch.solve_mip(qp, d_col, _int_cols(batch), opts)
    p = np.asarray(batch.p)
    real = p > 0.0
    feas_ks = np.asarray(res.feasible).reshape(K, S)
    inner_ks = np.asarray(res.inner).reshape(K, S)
    outer_ks = np.asarray(res.outer).reshape(K, S)
    out = []
    for k in range(K):
        feas = bool(np.all(np.where(real, feas_ks[k], True)))
        value = float(np.sum(np.where(real, p * inner_ks[k], 0.0))) \
            if feas else float("inf")
        out.append({
            "value": value,
            "value_lower": float(np.sum(np.where(real, p * outer_ks[k],
                                                 0.0))),
            "per_scenario": inner_ks[k],
            "feasible": feas,
            "xhat": np.asarray(
                jnp.where(batch.integer_slot, jnp.round(jnp.asarray(
                    xhats[k])), jnp.asarray(xhats[k]))),
        })
    return out


def first_stage_local_search(batch: ScenarioBatch, xhat0, inner0: float,
                             opts: BnBOptions = BnBOptions(),
                             max_rounds: int = 8,
                             verbose: bool = False) -> dict:
    """1-flip local search over the INTEGER first-stage slots, each
    round one batched evaluate_mip_many over all neighbors — the
    batched analog of slam/looper-style incumbent improvement, and the
    standard local-branching move for closing the inner side of a MIP
    bracket (no reference analog: Gurobi's heuristics play this role
    for the reference, ref:mpisppy/spopt.py:884)."""
    int_slots = np.nonzero(np.asarray(batch.integer_slot))[0]
    lb, ub = batch.nonant_box()
    best = np.asarray(xhat0, float).copy()
    best_val = float(inner0)
    for rnd in range(max_rounds):
        cands = []
        for j in int_slots:
            for v in (best[j] - 1.0, best[j] + 1.0):
                if lb[j] - 1e-6 <= v <= ub[j] + 1e-6:
                    c = best.copy()
                    c[j] = v
                    cands.append(c)
        evs = evaluate_mip_many(batch, cands, opts)
        vals = [e["value"] if e["feasible"] else float("inf") for e in evs]
        k = int(np.argmin(vals)) if vals else 0
        if not vals or vals[k] >= best_val - 1e-9:
            break
        best_val = vals[k]
        best = np.asarray(cands[k], float)
        if verbose:
            _console.log(f"[ls] round {rnd}: inner -> {best_val:.6g}",
                         level=_console.DEBUG)
    return {"xhat": best, "value": best_val}


def mip_dual_ascent_polyak(batch: ScenarioBatch, W, inner: float,
                           steps: int, opts: BnBOptions = BnBOptions(),
                           lam0: float = 1.0, target: float | None = None,
                           verbose: bool = False) -> dict:
    """Level-target subgradient ascent on the INTEGER Lagrangian dual:

        level_t = best_t + level_frac * (inner - best_t)
        step_t  = lam * max(level_t - L(W_t), 0) / ||g_t||_p^2,
        g_t     = x_t - xbar_t   (p-weighted node-mean-zero by
                                  construction, preserving the PH
                                  invariant that makes L(W) valid)

    with lam halved after two non-improving steps.  The raw Polyak rule
    (target = inner) overshoots badly when the duality-gap estimate is
    large (measured on sslp_15_45: step 1 dropped L by 12); aiming at a
    level strictly between the best bound and the incumbent is the
    standard stabilization (level-method style).  This is the classical
    dual-decomposition recipe (Caroe & Schultz) the reference's exact
    solvers make unnecessary (ref:mpisppy/cylinders/
    lagrangian_bounder.py gets L(W) from Gurobi's bestbound).  Each
    step is one batched scenario-MIP solve.  Stops early at `target`.
    Returns {'bound','W','history'}."""
    W = jnp.asarray(W)
    best, best_W = -float("inf"), W
    lam, since = float(lam0), 0
    level_frac = 0.3
    p = np.asarray(batch.p)
    hist = []
    for t in range(steps):
        lag = lagrangian_mip_bound(batch, W, opts)
        L = lag["bound"]
        hist.append(L)
        if verbose:
            _console.log(f"[polyak] step {t}: L = {L:.6g} (best {max(best, L):.6g}"
                  f", lam {lam:.3g})",
                         level=_console.DEBUG)
        if L > best:
            best, best_W = L, W
            since = 0
        else:
            since += 1
            if since >= 2:
                lam *= 0.5
                since = 0
        if target is not None and best >= target:
            break
        res = lag["result"]
        feas = np.asarray(res.feasible)
        if not bool(np.all(feas[p > 0.0])):
            break  # no integer point to take a subgradient from
        x_non = jnp.asarray(res.x)[:, batch.nonant_idx]
        xbar, _ = batch.node_average(x_non)
        g = x_non - xbar
        gnorm2 = float(jnp.sum(jnp.asarray(p)[:, None] * g * g))
        if gnorm2 <= 1e-12 or not np.isfinite(inner):
            break
        base = best if np.isfinite(best) else L
        level = base + level_frac * max(inner - base, 0.0)
        step = lam * max(level - L, 0.0) / gnorm2
        if step <= 0.0:
            break
        W = W + step * g
    return {"bound": best, "W": best_W, "history": hist}


def mip_dual_bundle(batch: ScenarioBatch, W, inner: float,
                    steps: int, opts: BnBOptions = BnBOptions(),
                    target: float | None = None,
                    trust0: float = 2.0,
                    verbose: bool = False) -> dict:
    """Trust-region BUNDLE method on the INTEGER Lagrangian dual — the
    upgrade over mip_dual_ascent_polyak's subgradient steps, which
    stall well short of the dual optimum (round 4: ~6 units above the
    sslp_15_45 optima after 12 steps).

    The dual D(W) = E_s[min_x f_s(x) + W_s'x_non] is concave; every
    oracle call at W_k returns
      * a CERTIFIED bound E_s[outer_s] (per-scenario B&B lower bounds,
        valid at any truncation — this is what gets REPORTED), and
      * a cut D(V) <= E_s[f_s(x_k,s) + V_s'x_non,k,s] from the
        per-scenario incumbents x_k (min <= value at any feasible
        point, so the cut is valid even when B&B is truncated).
    The master maximizes the cutting-plane model over the PH-invariant
    subspace (p-weighted node-mean of W = 0, which keeps D a valid
    bound) inside an inf-norm trust region around the best W; it is a
    ~(S*N)-variable LP solved on the host with scipy/HiGHS — a pure
    direction-finder: ANY W it proposes yields a certified bound from
    the oracle, so master quality never affects validity.

    Serious steps (realized improvement) expand the trust region; null
    steps shrink it.  Two-stage trees only (the mean-zero restriction
    is applied per ROOT slot).  Returns {'bound','W','history'}."""
    from scipy.optimize import linprog

    if batch.tree.num_stages != 2:
        raise ValueError("mip_dual_bundle: two-stage batches only")
    W = np.asarray(jnp.asarray(W), np.float64)
    p = np.asarray(batch.p, np.float64)
    real = p > 0.0
    S, N = W.shape
    nv = S * N
    cuts_a, cuts_b = [], []     # cut k: D(V) <= b_k + a_k . V
    best, best_W = -np.inf, W.copy()
    trust = float(trust0)
    hist = []
    center = W.copy()
    for t in range(steps):
        lag = lagrangian_mip_bound(batch, jnp.asarray(center + 0.0),
                                   opts) if t == 0 else \
            lagrangian_mip_bound(batch, jnp.asarray(W_try), opts)
        Wk = center if t == 0 else W_try
        L = lag["bound"]
        hist.append(L)
        # plain > when best is still -inf (the relative-eps form is
        # NaN-poisoned at -inf: -inf + inf = nan, and L > nan is False
        # forever)
        serious = (L > best if not np.isfinite(best)
                   else L > best + 1e-9 * max(1.0, abs(best)))
        if serious:
            best, best_W = L, Wk.copy()
            center = Wk.copy()
            trust = min(trust * 1.6, 1e4)
        else:
            trust = max(trust * 0.5, 1e-5)
        if verbose:
            _console.log(f"[bundle] step {t}: L={L:.6g} best={best:.6g} "
                  f"trust={trust:.3g}",
                         level=_console.DEBUG)
        if target is not None and best >= target:
            break
        res = lag["result"]
        feas = np.asarray(res.feasible)
        if bool(np.all(feas[real])):
            x_non = np.asarray(res.x)[:, np.asarray(batch.nonant_idx)]
            # res.inner is the LAGRANGIAN objective f_s(x_k)+W_k.x_non
            # (the oracle folds W into c via with_nonant_linear_quad);
            # the cut needs the RAW f_s(x_k), so subtract the penalty
            # evaluated at the incumbent
            wdot = np.sum(np.asarray(Wk) * x_non, axis=-1)
            fvals = np.asarray(res.inner) - wdot
            # cut: D(V) <= sum_s p_s f_s(x_k) + sum_s p_s V_s.x_non
            a = (p[:, None] * x_non).reshape(nv)
            b = float(np.sum(np.where(real, p * fvals, 0.0)))
            cuts_a.append(a)
            cuts_b.append(b)
        if not cuts_a:
            break
        # master LP: max t  s.t. t <= b_k + a_k.V, mean-zero, trust box
        nc = len(cuts_a)
        # vars: [V (nv), t (1)]
        c_lp = np.zeros(nv + 1)
        c_lp[-1] = -1.0                      # maximize t
        A_ub = np.zeros((nc, nv + 1))
        b_ub = np.zeros(nc)
        for k in range(nc):
            A_ub[k, :nv] = -cuts_a[k]
            A_ub[k, -1] = 1.0
            b_ub[k] = cuts_b[k]
        A_eq = np.zeros((N, nv + 1))
        for j in range(N):
            for s in range(S):
                A_eq[j, s * N + j] = p[s]
        b_eq = np.zeros(N)
        lb = np.concatenate([(center - trust).reshape(nv), [-np.inf]])
        ub = np.concatenate([(center + trust).reshape(nv), [np.inf]])
        sol = linprog(c_lp, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=b_eq,
                      bounds=np.stack([lb, ub], axis=1),
                      method="highs")
        if not sol.success:
            if verbose:
                _console.log(f"[bundle] master failed: {sol.message}")
            break
        W_try = sol.x[:nv].reshape(S, N)
        model_val = -sol.fun
        # model agrees with reality -> the dual is (locally) maxed out
        if np.isfinite(best) \
                and model_val <= best + 1e-7 * max(1.0, abs(best)) \
                and trust <= 1e-4:
            break
    return {"bound": best, "W": best_W, "history": hist}


def ef_mip(ef_problem, specs, opts: BnBOptions = BnBOptions(),
           verbose: bool = False) -> dict:
    """Exact MIP solve of an assembled extensive form (algos/ef.py
    EFProblem) — the correctness oracle for the decomposition bounds
    (ref:mpisppy/opt/ef.py:75-104's role).  Returns inner/outer/gap and
    the (S, n) per-scenario solution in original space.

    A one-shot oracle is a POLISH context (not a hot Lagrangian loop),
    so swap_rounds=0 (auto) promotes to bnb.POLISH_SWAP_ROUNDS here
    like the other final-candidate entry points."""
    opts = _polish_swap(opts)
    qp = ef_problem.qp
    n_tot = qp.c.shape[-1]
    n = ef_problem.n_per_scen
    S = len(specs)
    integer = np.zeros(n_tot, bool)
    for s, sp in enumerate(specs):
        if sp.integer is not None:
            integer[s * n:(s + 1) * n] = np.asarray(sp.integer, bool)
    cols = np.nonzero(integer)[0].astype(np.int32)
    qp1 = dataclasses.replace(
        qp, c=qp.c[None], q=qp.q[None], bl=qp.bl[None], bu=qp.bu[None],
        l=qp.l[None], u=qp.u[None])   # batch of one; A broadcasts
    d_col = jnp.asarray(ef_problem.scaling.d_col, qp.c.dtype)[None]
    res = _dispatch.solve_mip(qp1, d_col, cols, opts, verbose=verbose)
    x = np.asarray(res.x)[0].reshape(S, n)
    return {
        "inner": float(res.inner[0]),
        "outer": float(res.outer[0]),
        "gap": float(res.gap[0]),
        "x": x,
        "nodes": int(res.nodes_solved[0]),
        "result": res,
    }


def mip_dual_ascent(batch: ScenarioBatch, W: Array, rho: Array,
                    steps: int, opts: BnBOptions = BnBOptions()) -> dict:
    """Subgradient ascent on the MIP Lagrangian dual: each step solves
    the scenario MIPs at W (batched B&B), records the certified bound,
    and updates W += rho (x - xbar) from the INTEGER solutions — the
    exact-subproblem analog of the subgradient spoke
    (ref:mpisppy/cylinders/subgradient_bounder.py:12-54).  Returns the
    best certified bound and the W that achieved it."""
    best = -float("inf")
    best_W = W
    rho = jnp.asarray(rho)
    for _ in range(steps):
        lag = lagrangian_mip_bound(batch, W, opts)
        if lag["bound"] > best:
            best, best_W = lag["bound"], W
        res = lag["result"]
        feas = np.asarray(res.feasible)
        if not bool(np.all(feas[np.asarray(batch.p) > 0.0])):
            break  # no integer solution to take a subgradient from
        # res.x is already ORIGINAL space: gather the nonant columns
        x_non = jnp.asarray(res.x)[:, batch.nonant_idx]
        xbar, _ = batch.node_average(x_non)
        W = W + rho * (x_non - xbar)
    lag = lagrangian_mip_bound(batch, W, opts)
    if lag["bound"] > best:
        best, best_W = lag["bound"], W
    return {"bound": best, "W": best_W}


def _restrict_first_stage(batch: ScenarioBatch, qp, int_slots: np.ndarray,
                          lo: np.ndarray, hi: np.ndarray):
    """qp with the integer NONANT slots' box intersected with the
    ORIGINAL-space node box [lo, hi] (first-stage branching)."""
    S = batch.num_scenarios
    n = qp.c.shape[-1]
    l_full = jnp.broadcast_to(qp.l, (S, n))
    u_full = jnp.broadcast_to(qp.u, (S, n))
    cols = np.asarray(batch.nonant_idx)[int_slots]
    d = jnp.broadcast_to(batch.d_non, (S, batch.num_nonants))[:, int_slots]
    l_new = l_full.at[:, cols].max(jnp.asarray(lo, qp.c.dtype) / d)
    u_new = u_full.at[:, cols].min(jnp.asarray(hi, qp.c.dtype) / d)
    return dataclasses.replace(qp, l=l_new, u=u_new)


def decomposition_bnb(batch: ScenarioBatch, W,
                      opts: BnBOptions = BnBOptions(),
                      max_nodes: int = 30,
                      target_gap: float = 5e-3,
                      inner0: float = float("inf"),
                      xhat0=None,
                      node_fanout: int = 4,
                      verbose: bool = False) -> dict:
    """Branch-and-bound on the FIRST-STAGE integer nonants with
    scenario-decomposed bounds — the dual-decomposition B&B (ddsip /
    PIPS-SBB family) that closes duality gaps the root Lagrangian bound
    cannot.  This capability has no single reference call site: the
    reference outsources node solves to Gurobi on the EF or accepts the
    hub's root gap (ref:mpisppy/cylinders/hub.py:82-166); here every
    node's bound is itself a batched scenario-MIP solve (ops/bnb.py)
    and nodes are explored best-first on the host.

      node = a box on the integer first-stage slots (original space)
      bound(node) = E_s[ B&B outer bound of min f_s + W.x_non
                         s.t. x_non in node box ]   (valid: E[W] = 0)
      incumbent(node) = evaluate_mip at the node solution's rounded
                        probability-weighted mean, clipped into the box

    Node solves are COALESCED: up to `node_fanout` best-first nodes pop
    per round and their (fanout * S)-lane bound solves ride ONE
    megabatch dispatch through the scheduler (docs/dispatch.md) — the
    small-batch per-node dispatch storm was exactly what wedged the
    sslp_15_45 re-certification runs (round-5 verdict).  Fanning out
    only changes the SEARCH ORDER (standard parallel B&B: siblings
    solved before the best child's bound can prune them — at worst
    node_fanout-1 extra node solves per incumbent improvement); every
    bound remains certified and the bracket semantics are unchanged.

    Returns {'inner','outer','gap','xhat','nodes'}."""
    import heapq

    int_slots = np.nonzero(np.asarray(batch.integer_slot))[0]
    if int_slots.size == 0:
        raise ValueError("no integer first-stage slots to branch on")
    lb_all, ub_all = batch.nonant_box()
    lo0 = np.ceil(lb_all[int_slots] - 1e-6)
    hi0 = np.floor(ub_all[int_slots] + 1e-6)

    zeros = jnp.zeros_like(W)
    qp_W = batch.with_nonant_linear_quad(W, zeros)
    int_cols = _int_cols(batch)
    p = np.asarray(batch.p)
    real = p > 0.0

    inner = float(inner0)
    xhat_best = None if xhat0 is None else np.asarray(xhat0)
    fathom_floor = float("inf")
    tried: set[tuple] = set()
    heap: list = []
    counter = 0
    heapq.heappush(heap, (-np.inf, counter, lo0, hi0))
    nodes = 0
    failed_nodes = 0

    def scale(v):
        return max(1.0, abs(v)) if np.isfinite(v) else 1.0

    sched = _dispatch.get_scheduler()
    fanout = max(1, int(node_fanout))
    while heap and nodes < max_nodes:
        # pop up to `fanout` surviving best-first nodes and submit them
        # together: the scheduler coalesces the same-key submits into
        # ONE (popped * S)-lane megabatch dispatch (see docstring)
        popped = []
        while heap and len(popped) < fanout \
                and nodes + len(popped) < max_nodes:
            node_bound, _, lo, hi = heapq.heappop(heap)
            if np.isfinite(inner) \
                    and node_bound >= inner - target_gap * scale(inner):
                fathom_floor = min(fathom_floor, node_bound)
                continue
            popped.append((node_bound, lo, hi))
        if not popped:
            break
        # build every node qp BEFORE submitting: the submits then land
        # back-to-back inside one admission window instead of racing
        # the max-wait timer against qp construction
        qp_nodes = [_restrict_first_stage(batch, qp_W, int_slots, lo, hi)
                    for _, lo, hi in popped]
        tickets = [sched.submit(qpn, batch.d_col, int_cols, opts)
                   for qpn in qp_nodes]
        for (node_bound, lo, hi), ticket in zip(popped, tickets):
            try:
                res = ticket.result()
            except _dispatch.SolveFailed as e:
                # quarantined node solve (docs/dispatch.md failure
                # semantics): the node's PARENT bound is still a valid
                # lower bound on everything under it, so folding it
                # into the fathom floor keeps the reported outer bound
                # certified — the node is abandoned (never re-queued:
                # a poison node would loop forever), accounted, and the
                # healthy nodes proceed
                nodes += 1
                failed_nodes += 1
                fathom_floor = min(fathom_floor, node_bound)
                _console.log(f"[ddbnb] node solve quarantined "
                             f"({e.reason}): holding parent bound "
                             f"{node_bound:.6g}", level=_console.DEBUG)
                continue
            nodes += 1
            outer_s = np.asarray(res.outer)
            nb = float(np.sum(np.where(real, p * outer_s, 0.0)))
            nb = max(nb, node_bound)  # parent bound still valid

            feas_s = np.asarray(res.feasible)
            if bool(np.all(feas_s[real])):
                x_non = np.asarray(res.x)[:, np.asarray(batch.nonant_idx)]
                xbar = (p[:, None] * x_non).sum(0)
                cand = xbar.copy()
                cand[int_slots] = np.clip(np.round(xbar[int_slots]),
                                          lo, hi)
                key = tuple(np.round(cand[int_slots]).astype(int))
                if key not in tried:
                    tried.add(key)
                    try:
                        ev = evaluate_mip(batch,
                                          jnp.asarray(cand, np.float32),
                                          opts)
                    except _dispatch.SolveFailed as e:
                        # the incumbent candidate eval is optional work:
                        # a quarantined eval costs one candidate, never
                        # the run (the search keeps its bracket)
                        _console.log(f"[ddbnb] incumbent eval "
                                     f"quarantined ({e.reason}); "
                                     f"skipping candidate",
                                     level=_console.DEBUG)
                        ev = None
                    if ev is not None and ev["feasible"] \
                            and ev["value"] < inner:
                        inner, xhat_best = ev["value"], ev["xhat"]
                spread = (p[:, None] * np.abs(
                    x_non - xbar[None, :])).sum(0)[int_slots]
            else:
                # no integer solution in some scenario: branch on width
                spread = (hi - lo).astype(float)

            if np.isfinite(inner) \
                    and nb >= inner - target_gap * scale(inner):
                fathom_floor = min(fathom_floor, nb)
                if verbose:
                    _console.log(f"[ddbnb] node {nodes}: fathomed at "
                                 f"{nb:.6g} (inner {inner:.6g})",
                                 level=_console.DEBUG)
                continue
            branchable = hi > lo
            if not bool(np.any(branchable)):
                fathom_floor = min(fathom_floor, nb)  # leaf: exact-ish
                continue
            j = int(np.argmax(np.where(branchable, spread, -1.0)))
            if bool(np.all(feas_s[real])):
                split = float(np.clip(
                    np.floor((p[:, None] * x_non).sum(0)[int_slots][j]),
                    lo[j], hi[j] - 1))
            else:
                split = float(np.floor(0.5 * (lo[j] + hi[j])))
            lo_up = lo.copy()
            hi_dn = hi.copy()
            hi_dn[j] = split
            lo_up[j] = split + 1.0
            counter += 1
            heapq.heappush(heap, (nb, counter, lo, hi_dn))
            counter += 1
            heapq.heappush(heap, (nb, counter, lo_up, hi))
            if verbose:
                _console.log(f"[ddbnb] node {nodes}: bound {nb:.6g} "
                             f"inner {inner:.6g} branch slot "
                             f"{int_slots[j]} at {split}",
                             level=_console.DEBUG)

    open_min = min((b for b, *_ in heap), default=float("inf"))
    outer = min(open_min, fathom_floor, inner)
    gap = (inner - outer) / scale(inner) if np.isfinite(inner) else float("inf")
    return {"inner": inner, "outer": outer, "gap": gap,
            "xhat": xhat_best, "nodes": nodes,
            "failed_nodes": failed_nodes}


@dataclasses.dataclass
class MIPGapResult:
    inner: float          # certified upper bound (integer-feasible)
    outer: float          # certified lower bound
    gap: float            # (inner - outer) / max(1, |inner|)
    xhat: np.ndarray      # the first stage achieving `inner`
    trivial_bound: float  # LP wait-and-see bound from PH iter0
    ph_conv: float


def certified_mip_gap(batch: ScenarioBatch, ph_options=None,
                      opts: BnBOptions = BnBOptions(),
                      ascent_steps: int = 0,
                      n_shuffle: int = 2,
                      target_gap: float = 5e-3,
                      dd_nodes: int = 30,
                      verbose: bool = False) -> MIPGapResult:
    """End-to-end certified MIP gap for a two-stage integer problem:

      1. LP-relaxed PH for converged (W, xbar) — the hot TPU loop;
      2. candidate first stages (rounded xbar, slam-max/min, a few
         scenario vectors), ranked by cheap LP-recourse evaluation;
      3. the best candidate MIP-evaluated (certified inner bound);
      4. Lagrangian MIP bound at W (+ optional dual ascent steps);
      5. if the root gap still exceeds `target_gap`: first-stage
         branch-and-bound over the decomposition (decomposition_bnb)
         until the certified gap closes or `dd_nodes` runs out.

    This is the pipeline the reference runs as hub + xhatshuffle +
    Lagrangian spokes with exact MIP subproblem solves
    (ref:mpisppy/generic_cylinders.py:109-312), collapsed into batched
    tensor programs — plus the node search the reference leaves to the
    EF solver."""
    from mpisppy_tpu.algos import ph as ph_mod
    from mpisppy_tpu.algos import xhat as xhat_mod

    ph_options = ph_options or ph_mod.PHOptions(max_iterations=50)
    driver = ph_mod.PH(ph_options, batch)
    conv, _, trivial = driver.ph_main()
    st = driver.state

    # -- candidates --------------------------------------------------------
    x_non = batch.nonants(st.solver.x)
    cands = [xhat_mod.round_integers(batch, st.xbar_nodes[0])]
    cands.append(xhat_mod.slam_candidate(batch, x_non, sense_max=True))
    cands.append(xhat_mod.slam_candidate(batch, x_non, sense_max=False))
    S = batch.num_real
    for s in range(min(n_shuffle, S)):
        cands.append(xhat_mod.round_integers(batch, x_non[s]))
    # wait-and-see INTEGER candidates: a few scenarios' own exact-MIP
    # first stages (one cheap batched B&B on a SLICE of the plain
    # batch).  At a converged PH the shuffle candidates above all equal
    # the consensus point, whose integer-recourse value can be far off —
    # the WS solutions are the diverse, integral pool the reference's
    # shuffle looper effectively draws from (it solves subproblems as
    # MIPs).
    k_ws = min(S, 8)

    def _head(x, batched_ndim):
        if hasattr(x, "vals"):  # EllMatrix
            return dataclasses.replace(x, vals=_head(x.vals, batched_ndim))
        return x[:k_ws] if getattr(x, "ndim", 0) == batched_ndim else x

    qp_ws = dataclasses.replace(
        batch.qp, c=batch.qp.c[:k_ws], q=batch.qp.q[:k_ws],
        A=_head(batch.qp.A, 3),
        bl=_head(batch.qp.bl, 2), bu=_head(batch.qp.bu, 2),
        l=_head(batch.qp.l, 2), u=_head(batch.qp.u, 2))
    ws = _dispatch.solve_mip(qp_ws, _head(batch.d_col, 2), _int_cols(batch),
                       opts)
    ws_x = np.asarray(ws.x)[:, np.asarray(batch.nonant_idx)]
    ws_feas = np.asarray(ws.feasible)
    int_slot = np.asarray(batch.integer_slot)
    seen_keys = set()
    for s in range(k_ws):
        if not ws_feas[s]:
            continue
        # round only the INTEGER slots; continuous first-stage
        # coordinates keep the scenario's exact values
        cand = np.where(int_slot, np.round(ws_x[s]), ws_x[s])
        key = tuple(np.round(cand[int_slot]).astype(int))
        if key in seen_keys:
            continue
        seen_keys.add(key)
        cands.append(jnp.asarray(cand, batch.qp.c.dtype))
    lp_vals = [float(xhat_mod.evaluate(batch, c, opts.lp).value)
               for c in cands]
    order = np.argsort(lp_vals)

    # -- certified inner: MIP-evaluate candidates in LP rank order; try
    #    a few past the first success (LP rank is a good but imperfect
    #    predictor of the integer-recourse value) -----------------------
    inner, xhat_best = float("inf"), np.asarray(cands[int(order[0])])
    n_eval = 0
    for i in order:
        ev = evaluate_mip(batch, cands[int(i)], opts)
        n_eval += 1
        if ev["feasible"] and ev["value"] < inner:
            inner, xhat_best = ev["value"], ev["xhat"]
        if np.isfinite(inner) and n_eval >= 3:
            break

    # -- certified outer ---------------------------------------------------
    if ascent_steps > 0:
        asc = mip_dual_ascent(batch, st.W, st.rho, ascent_steps, opts)
        outer, W_best = asc["bound"], asc["W"]
    else:
        outer = lagrangian_mip_bound(batch, st.W, opts)["bound"]
        W_best = st.W

    gap = (inner - outer) / max(1.0, abs(inner))

    # -- close the duality gap with first-stage branching ------------------
    if gap > target_gap and dd_nodes > 0 \
            and bool(np.any(np.asarray(batch.integer_slot))):
        dd = decomposition_bnb(batch, W_best, opts, max_nodes=dd_nodes,
                               target_gap=target_gap, inner0=inner,
                               xhat0=xhat_best, verbose=verbose)
        inner = min(inner, dd["inner"])
        outer = max(outer, dd["outer"])
        if dd["xhat"] is not None and dd["inner"] <= inner:
            xhat_best = dd["xhat"]
        gap = (inner - outer) / max(1.0, abs(inner))

    return MIPGapResult(inner=inner, outer=outer, gap=gap, xhat=xhat_best,
                        trivial_bound=trivial, ph_conv=conv)
