###############################################################################
# WheelSpinner: top-level orchestration (ref:mpisppy/spin_the_wheel.py:18-242).
#
# The reference splits COMM_WORLD into a (strata x cylinder) process grid
# and runs one opt object + SPCommunicator per rank
# (ref:spin_the_wheel.py:224-242 _make_comms).  Here all cylinders drive
# ONE device mesh from one host process: the hub's PH loop and every
# spoke's batched solve are enqueued on the same XLA stream, overlapping
# like the reference's concurrent cylinders, and the scenario axis is the
# mesh axis.  hub_dict / list_of_spoke_dicts keep the reference's shape:
#
#   hub_dict = {"hub_class": PHHub, "hub_kwargs": {"options": {...}},
#               "opt_class": PH, "opt_kwargs": {...}}
#   spoke_dict = {"spoke_class": LagrangianOuterBound,
#                 "opt_kwargs": {"options": {...}}}
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu import global_toc
from mpisppy_tpu.resilience.faults import PreemptionError


class WheelSpinner:
    """ref:mpisppy/spin_the_wheel.py:18."""

    def __init__(self, hub_dict: dict, list_of_spoke_dict=None):
        self.hub_dict = hub_dict
        self.list_of_spoke_dict = list_of_spoke_dict or []
        self.spcomm = None
        self.opt = None
        self.on_hub = True  # single-process: we always "are" the hub
        self.preempted = False

    def build(self):
        """Construct opt + spokes + hub without running (split out so a
        checkpoint can be restored into the built objects before
        spin())."""
        if self.spcomm is not None:
            return self
        hd = self.hub_dict
        opt_class = hd["opt_class"]
        self.opt = opt_class(**hd.get("opt_kwargs", {}))

        spokes = []
        for sd in self.list_of_spoke_dict:
            spoke_class = sd["spoke_class"]
            kw = dict(sd.get("opt_kwargs", {}))
            spokes.append(spoke_class(self.opt, kw.get("options", kw)))

        hub_class = hd["hub_class"]
        hub_kwargs = dict(hd.get("hub_kwargs", {}))
        self.spcomm = hub_class(self.opt, hub_kwargs.get("options", {}),
                                spokes=spokes)
        self.spcomm.make_windows()
        self.spcomm.setup_hub()
        return self

    def spin(self, comm_world=None):
        """Build opt + hub + spokes, run the hub algorithm to
        completion, terminate + finalize the spokes
        (ref:spin_the_wheel.py:43-149 run()).

        Preemption tolerance (docs/resilience.md): when the hub is
        configured with a checkpoint_path, SIGTERM/SIGINT are converted
        to PreemptionError, which triggers one SYNCHRONOUS emergency
        checkpoint before re-raising — on a preemptible TPU pool the
        eviction signal arrives seconds before the kill, exactly enough
        for a last-gasp save.  A later run restores via
        hub.load_checkpoint and resumes mid-loop."""
        self.build()
        global_toc("Starting wheel spin", False)
        ckpt_path = self.spcomm.options.get("checkpoint_path")
        prev_handlers = self._install_preemption_handlers() \
            if ckpt_path else None
        try:
            self.spcomm.main()
        except PreemptionError as e:
            self.preempted = True
            if ckpt_path:
                saved = self.spcomm.emergency_checkpoint(ckpt_path)
                global_toc(
                    f"preempted: emergency checkpoint "
                    f"{'written to ' + ckpt_path if saved else 'SKIPPED'}"
                    f" at hub iter {self.spcomm._iter}", True)
            # run-end with an explicit exit reason + black-box dump,
            # AFTER the emergency save (the save must win the race for
            # the eviction grace window; docs/telemetry.md)
            self._record_crash("preemption", e)
            raise
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            self._record_crash("exception", e)
            raise
        finally:
            self._restore_preemption_handlers(prev_handlers)
            # drop this thread's dispatch session token (ISSUE 12):
            # the run is over — a later wheel (or bare scheduler use)
            # on this thread must not inherit a dead run's stamp
            try:
                from mpisppy_tpu import dispatch as _dispatch
                _dispatch.clear_session_context()
            except Exception:
                pass
        self.spcomm.send_terminate()
        self.spcomm.finalize()
        self.spcomm.hub_finalize()
        self.spcomm.free_windows()
        return self

    def _record_crash(self, reason: str, exc: BaseException) -> None:
        """Last words of a dying wheel: emit the run-end event (exit
        reason + final gap) and dump any flight-recorder black box
        subscribed to the hub's bus to flight-<runid>.jsonl.  Best
        effort by construction — the original exception keeps
        propagating whatever happens here."""
        detail = f"{type(exc).__name__}: {exc}"
        try:
            # the wheel is dying on an explicit exception, not a hang:
            # the progress watchdog must not also trip (and abort the
            # process out from under the caller's unwind)
            wd = getattr(self.spcomm, "_watchdog", None)
            if wd is not None:
                wd.stop()
        except Exception:
            pass
        try:
            self.spcomm.emit_run_end(reason, error=detail)
        except Exception:
            pass
        try:
            from mpisppy_tpu.telemetry import flightrec
            bus = getattr(self.spcomm, "telemetry", None)
            for path in flightrec.dump_all(bus, reason=detail):
                if path:
                    global_toc(f"flight recorder: black box written "
                               f"to {path}", True)
        except Exception:
            pass

    # -- preemption signal plumbing ---------------------------------------
    @staticmethod
    def _install_preemption_handlers():
        """SIGTERM/SIGINT -> PreemptionError (raised at the next
        bytecode boundary of the host loop, i.e. between device
        dispatches).  Returns the previous handlers for restoration;
        None when not on the main thread (signal.signal would raise)."""
        import signal
        import threading
        if threading.current_thread() is not threading.main_thread():
            return None

        fired = []

        def _handler(signum, frame):
            # latch: a second SIGTERM/SIGINT (impatient scheduler,
            # double Ctrl-C) must not unwind the emergency save that
            # the FIRST signal triggered — the partial .tmp would never
            # be renamed and the last-gasp snapshot would be lost
            if fired:
                return
            fired.append(signum)
            raise PreemptionError(f"received signal {signum}")

        prev = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.signal(sig, _handler)
        return prev

    @staticmethod
    def _restore_preemption_handlers(prev):
        if not prev:
            return
        import signal
        for sig, h in prev.items():
            signal.signal(sig, h)

    # -- results (ref:spin_the_wheel.py:151-222) --------------------------
    @property
    def BestInnerBound(self):
        return self.spcomm.BestInnerBound

    @property
    def BestOuterBound(self):
        return self.spcomm.BestOuterBound

    def write_first_stage_solution(self, solution_file_name: str):
        """npy/csv first-stage (ROOT) solution
        (ref:spin_the_wheel.py:171-195)."""
        nodes = self.spcomm.best_nonants()
        root = nodes[0]
        stage1 = root[np.nonzero(
            self.opt.batch.tree.slot_stage == 1)[0]]
        if solution_file_name.endswith(".npy"):
            np.save(solution_file_name, stage1)
        else:
            with open(solution_file_name, "w") as f:
                for i, v in enumerate(stage1):
                    f.write(f"x{i},{v}\n")

    def write_tree_solution(self, directory_name: str):
        """Per-node nonant values, one file per tree node
        (ref:spin_the_wheel.py:197-222)."""
        import os
        os.makedirs(directory_name, exist_ok=True)
        nodes = self.spcomm.best_nonants()
        tree = self.opt.batch.tree
        for nid in range(tree.num_nodes):
            name = tree.node_name(nid)
            stage = int(np.searchsorted(
                np.cumsum(tree.nodes_per_stage), nid, side="right")) + 1
            slots = np.nonzero(tree.slot_stage == stage)[0]
            with open(os.path.join(directory_name, f"{name}.csv"), "w") as f:
                for i in slots:
                    f.write(f"slot{i},{nodes[nid, i]}\n")
