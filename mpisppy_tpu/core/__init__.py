# core subpackage of mpisppy_tpu
