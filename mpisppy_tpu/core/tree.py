###############################################################################
# Scenario trees, TPU-style.
#
# The reference represents a scenario tree as per-scenario lists of
# ScenarioNode objects hanging off Pyomo models, parsed into a _ScenTree
# with per-node MPI communicators (ref:mpisppy/scenario_tree.py:51,
# ref:mpisppy/utils/sputils.py:691-856, ref:mpisppy/spbase.py:337-379).
# Here the tree is *static metadata* (hashable, safe as a jit static arg)
# plus two small index arrays:
#
#   * every scenario carries one nonant value per "slot"; a slot is one
#     (stage, variable) pair, so the nonant vector has the same length N
#     for every scenario;
#   * `node_of_slot[s, i]` maps scenario s's slot i to the global id of
#     the tree node that owns it.  Nonanticipativity is then a *segmented
#     reduction*: slots sharing a (node, slot) key are averaged.  On a
#     device mesh the segment-sum is followed by a cross-device psum —
#     the analog of the reference's one-Allreduce-per-node-comm
#     (ref:mpisppy/phbase.py:88-92) without any communicator objects.
#
# Trees are balanced with per-stage branching factors, matching the
# reference's ROOT/ROOT_0/ROOT_0_1 naming scheme
# (ref:mpisppy/utils/sputils.py:992-1034).  A two-stage problem is the
# special case branching_factors=(S,) with the single node ROOT.
###############################################################################
from __future__ import annotations

import dataclasses
import math

import numpy as np


@dataclasses.dataclass(frozen=True)
class ScenarioTree:
    """Balanced scenario tree metadata.

    branching_factors: (b1, ..., b_{T-1}); num scenarios = prod(b).
    nonants_per_stage: number of nonant variables declared at each
        non-leaf stage (length T-1).  Two-stage: (N,).
    """

    branching_factors: tuple[int, ...]
    nonants_per_stage: tuple[int, ...]

    def __post_init__(self):
        if len(self.branching_factors) != len(self.nonants_per_stage):
            raise ValueError("branching_factors and nonants_per_stage must "
                             "have one entry per non-leaf stage")

    @property
    def num_stages(self) -> int:
        return len(self.branching_factors) + 1

    @property
    def num_scenarios(self) -> int:
        return math.prod(self.branching_factors)

    @property
    def num_nonant_slots(self) -> int:
        return sum(self.nonants_per_stage)

    @property
    def nodes_per_stage(self) -> tuple[int, ...]:
        """Non-leaf node count at stage t = prod(b[:t-1]); stage 1 -> 1."""
        out, acc = [], 1
        for b in self.branching_factors:
            out.append(acc)
            acc *= b
        return tuple(out)

    @property
    def num_nodes(self) -> int:
        return sum(self.nodes_per_stage)

    @property
    def stage_node_offset(self) -> tuple[int, ...]:
        """Global node-id offset of each non-leaf stage's first node."""
        offs, acc = [], 0
        for c in self.nodes_per_stage:
            offs.append(acc)
            acc += c
        return tuple(offs)

    @property
    def slot_stage(self) -> np.ndarray:
        """(N,) stage index (1-based) of each nonant slot."""
        return np.concatenate([
            np.full(n, t + 1, np.int32)
            for t, n in enumerate(self.nonants_per_stage)
        ]) if self.num_nonant_slots else np.zeros(0, np.int32)

    def scen_node_at_stage(self, scen: np.ndarray, stage: int) -> np.ndarray:
        """Global node id of `scen` (0-based) at non-leaf `stage` (1-based).

        Scenarios are numbered depth-first, so the stage-t node of
        scenario s is s // (scenarios per stage-t node) — the same
        contiguous-slice layout as the reference's _ScenTree
        (ref:mpisppy/utils/sputils.py:790-856).
        """
        per_node = math.prod(self.branching_factors[stage - 1:])
        return self.stage_node_offset[stage - 1] + scen // per_node

    def node_of_slot(self) -> np.ndarray:
        """(S, N) global node id owning each scenario's nonant slot."""
        s = np.arange(self.num_scenarios)
        cols = []
        for t, n in enumerate(self.nonants_per_stage):
            node = self.scen_node_at_stage(s, t + 1)
            cols.append(np.repeat(node[:, None], n, axis=1))
        if not cols:
            return np.zeros((self.num_scenarios, 0), np.int32)
        return np.concatenate(cols, axis=1).astype(np.int32)

    def node_name(self, node_id: int) -> str:
        """ROOT / ROOT_i / ROOT_i_j naming (ref:mpisppy/utils/sputils.py:992)."""
        offs = self.stage_node_offset
        stage = max(t for t, o in enumerate(offs) if o <= node_id) + 1
        rel = node_id - offs[stage - 1]
        parts = []
        for t in range(stage - 1, 0, -1):
            b = self.branching_factors[t - 1]
            parts.append(rel % b)
            rel //= b
        return "_".join(["ROOT"] + [str(p) for p in reversed(parts)])

    def all_nodenames(self) -> list[str]:
        return [self.node_name(i) for i in range(self.num_nodes)]


def two_stage_tree(num_scenarios: int, num_nonants: int) -> ScenarioTree:
    """The common case: one ROOT node owning all first-stage variables."""
    return ScenarioTree(branching_factors=(num_scenarios,),
                        nonants_per_stage=(num_nonants,))
