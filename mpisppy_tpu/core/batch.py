###############################################################################
# Scenario batch: the data plane of the framework.
#
# The reference instantiates one Pyomo ConcreteModel per scenario via a
# user `scenario_creator` and keeps per-variable maps into it
# (ref:mpisppy/spbase.py:259-334).  Here a scenario is a declarative
# numpy spec of a BoxQP plus a nonant column map, and a *batch* of
# scenarios is one pytree of stacked arrays with a leading scenario axis
# — HBM-resident, shardable over a mesh axis, and consumed whole by the
# batched PDHG kernel.  This is the TPU answer to the reference's
# "scenarios_creator + attach _mpisppy_data" glue:
#
#   specs (host, numpy)  --from_specs-->  ScenarioBatch (device pytree)
#
# Ruiz equilibration is applied at build time; PH-layer math (prox
# terms, W vectors, xbar averaging) happens in ORIGINAL variable space
# and is mapped into the scaled space via the stored column scalings, so
# averaging across scenarios stays meaningful even with per-scenario
# scalings.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.ops.boxqp import BoxQP, ruiz_scale
from mpisppy_tpu.core.tree import ScenarioTree, two_stage_tree

Array = jax.Array


@dataclasses.dataclass
class ScenarioSpec:
    """One scenario's subproblem in original (unscaled) space.

    The analog of the reference's scenario_creator output
    (ref:examples/farmer/farmer.py:31-89): a model plus its nonant
    declaration (`sputils.attach_root_node`) and probability.

    nonant_idx: column indices of nonanticipative variables, ordered
    stage-major for multistage problems.  All scenarios of a batch must
    use the same column layout.
    """

    name: str
    c: np.ndarray
    A: np.ndarray
    bl: np.ndarray
    bu: np.ndarray
    l: np.ndarray  # noqa: E741
    u: np.ndarray
    nonant_idx: np.ndarray
    q: np.ndarray | None = None
    probability: float | None = None  # None -> uniform
    integer: np.ndarray | None = None  # bool over all n columns
    # per-slot nonant weights for variable-probability problems
    # (ref:mpisppy/spbase.py:398-441): weight 0 marks a slot absent from
    # this scenario (admm wrappers); None -> ordinary probabilities.
    var_prob: np.ndarray | None = None  # (N,) weights
    # second-order-cone row blocks: a list of int row-index arrays,
    # HEAD FIRST (rows (t; z) with a_head'x - b >= ||(Ax - b)_tail||);
    # SOC rows must carry bl == bu == b.  The cone PATTERN must be
    # identical across the batch (like the nonant layout).  None -> a
    # pure box problem (ops/cones.py documents the full contract).
    soc_blocks: list | None = None


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["qp", "d_col", "d_row", "d_non", "p", "nonant_idx",
                 "node_of_slot", "integer_slot", "integer_full", "var_prob"],
    meta_fields=["tree", "num_real"],
)
@dataclasses.dataclass(frozen=True)
class ScenarioBatch:
    """All scenarios of a problem as one device pytree.

    qp:           scaled, batched BoxQP (leading axis S; A may be (m,n)
                  shared when the constraint matrix is deterministic).
    d_col/d_row:  Ruiz scalings; x_orig = d_col * x_scaled.  (n,)/(m,) if
                  shared across the batch else (S,n)/(S,m).
    d_non:        d_col gathered at nonant columns ((N,) or (S,N)).
    p:            (S,) scenario probabilities (padded scenarios get 0).
    nonant_idx:   (N,) int32 nonant column indices (shared layout).
    node_of_slot: (S, N) int32 owning tree-node id per scenario slot.
    integer_slot: (N,) bool integrality of each nonant slot.
    integer_full: (n,) bool integrality of EVERY column (the exact-MIP
                  path, ops/bnb.py, branches over all of these).
    tree:         static ScenarioTree metadata.
    num_real:     scenarios before mesh padding.
    """

    qp: BoxQP
    d_col: Array
    d_row: Array
    d_non: Array
    p: Array
    nonant_idx: Array
    node_of_slot: Array
    integer_slot: Array
    integer_full: Array
    tree: ScenarioTree
    num_real: int
    # (S, N) per-(scenario, slot) nonant weights, or None for ordinary
    # probability weighting (ref:mpisppy/spbase.py:398-441 prob_coeff).
    # Weight 0 marks a slot ABSENT from that scenario (admm wrappers);
    # reductions then average only over the scenarios that carry it.
    var_prob: Array | None = None

    @property
    def num_scenarios(self) -> int:
        return self.qp.c.shape[0]

    @property
    def num_nonants(self) -> int:
        return int(self.nonant_idx.shape[0])

    # ---- original-space views -------------------------------------------
    def nonants(self, x_scaled: Array) -> Array:
        """(S, N) original-space nonant values from scaled iterates."""
        return self.d_non * x_scaled[..., self.nonant_idx]

    def objective(self, x_scaled: Array) -> Array:
        """Per-scenario ORIGINAL objective.  Scaled c,q absorb d_col, so
        evaluating the scaled quadratic at scaled x is the original value."""
        return jnp.sum(self.qp.c * x_scaled + 0.5 * self.qp.q * x_scaled**2,
                       axis=-1)

    def node_average(self, vals: Array, weights: Array | None = None):
        """Probability-weighted mean of per-scenario slot values within
        each owning tree node — the framework's ONE nonanticipativity
        reduction, replacing the reference's per-node-communicator
        Allreduces (ref:mpisppy/phbase.py:32-112, spbase.py:337-379).

        vals: (S, N).  weights: optional (S, N) per-(scenario, slot)
        weights (variable-probability support,
        ref:mpisppy/spbase.py:398-441); defaults to p broadcast.

        Returns (avg_per_scen (S, N), avg_nodes (num_nodes, N)).  Under
        jit over a sharded scenario axis the sums become cross-device
        all-reduces automatically.
        """
        if weights is None:
            weights = self.var_prob  # may still be None
        w = self.p[:, None] if weights is None else weights
        tiny = jnp.asarray(1e-30, vals.dtype)
        if self.tree.num_nodes == 1:
            num = jnp.sum(w * vals, axis=0)
            den = jnp.sum(jnp.broadcast_to(w, vals.shape), axis=0)
            avg = num / jnp.maximum(den, tiny)
            return jnp.broadcast_to(avg, vals.shape), avg[None, :]
        N = self.num_nonants
        nseg = self.tree.num_nodes * N
        key = (self.node_of_slot * N + jnp.arange(N)[None, :]).reshape(-1)
        num = jax.ops.segment_sum((w * vals).reshape(-1), key,
                                  num_segments=nseg)
        den = jax.ops.segment_sum(
            jnp.broadcast_to(w, vals.shape).reshape(-1), key,
            num_segments=nseg)
        avg_nodes = (num / jnp.maximum(den, tiny)).reshape(
            self.tree.num_nodes, N)
        avg_scen = jnp.take_along_axis(avg_nodes, self.node_of_slot, axis=0)
        return avg_scen, avg_nodes

    def nonant_box(self) -> "tuple[np.ndarray, np.ndarray]":
        """(lb, ub) of the nonant slots in ORIGINAL space: the tightest
        intersection across scenarios (host arrays; static per batch)."""
        nonant_idx = np.asarray(self.nonant_idx)
        S = self.num_scenarios
        d = np.broadcast_to(np.asarray(self.d_non), (S, len(nonant_idx)))
        l_s = np.broadcast_to(np.asarray(self.qp.l),
                              (S, self.qp.n))[:, nonant_idx] * d
        u_s = np.broadcast_to(np.asarray(self.qp.u),
                              (S, self.qp.n))[:, nonant_idx] * d
        return l_s.max(0), u_s.min(0)

    def expectation(self, vals: Array) -> Array:
        """E[vals] over scenarios — Eobjective/Ebound style reduction
        (ref:mpisppy/spopt.py:344-436)."""
        return jnp.sum(self.p * vals)

    def with_nonant_linear_quad(self, w: Array, rho_quad: Array) -> BoxQP:
        """Return a qp whose objective adds, in ORIGINAL space,
        w·x_non + 1/2 x_non' diag(rho_quad) x_non over the nonant slots.

        This is the whole PH objective plumbing
        (ref:mpisppy/phbase.py:670-760) reduced to two elementwise maps:
        original-space linear/diagonal-quadratic terms transform into
        scaled space as c += d_non*w and q += d_non^2*rho (then scattered
        to full columns).
        """
        c_add = jnp.zeros_like(self.qp.c).at[..., self.nonant_idx].add(
            self.d_non * w)
        q_add = jnp.zeros_like(self.qp.q).at[..., self.nonant_idx].add(
            self.d_non * self.d_non * rho_quad)
        return dataclasses.replace(self.qp, c=self.qp.c + c_add,
                                   q=self.qp.q + q_add)

    def with_fixed_nonants(self, xhat_nodes: Array) -> BoxQP:
        """Fix each scenario's nonants to its tree nodes' values
        (original space) by collapsing the box to a point — the batched
        analog of _fix_nonants (ref:mpisppy/spopt.py:633-674).

        xhat_nodes: (num_nodes, N) per-node candidate values, or (N,) for
        the two-stage case.
        """
        if xhat_nodes.ndim == 2:
            xhat = jnp.take_along_axis(xhat_nodes, self.node_of_slot, axis=0)
        else:
            xhat = jnp.broadcast_to(xhat_nodes, self.node_of_slot.shape)
        xs = xhat / self.d_non  # to scaled space; (S, N)
        S, n = self.qp.c.shape
        l_full = jnp.broadcast_to(self.qp.l, (S, n))
        u_full = jnp.broadcast_to(self.qp.u, (S, n))
        return dataclasses.replace(
            self.qp,
            l=l_full.at[:, self.nonant_idx].set(xs),
            u=u_full.at[:, self.nonant_idx].set(xs),
        )


def concretize(batch):
    """Realize a scengen VirtualBatch into a plain ScenarioBatch; a
    ScenarioBatch passes through untouched.  Every jitted iteration
    kernel calls this at entry, so synthesized scenario data exists
    only as transients inside one device program (docs/scengen.md) —
    the seam that decouples scenario count from resident memory."""
    if getattr(batch, "is_virtual", False):
        return batch.realize()
    return batch


def scale_field(name: str, val, d_row, d_col):
    """Apply a SHARED Ruiz scaling to one qp field — the single
    arithmetic both scengen synthesis paths share (from_specs with a
    precomputed `scaling`, and VirtualBatch.realize in-trace), so
    host materialization and device synthesis are bit-identical: each
    field is converted to the working dtype FIRST and then scaled with
    the same f32 elementwise ops, in the same order."""
    if name == "c":
        return val * d_col
    if name == "q":
        return val * d_col * d_col
    if name in ("l", "u"):
        return val / d_col
    if name in ("bl", "bu"):
        return val * d_row
    if name == "A":
        if hasattr(val, "vals"):  # ops.sparse.EllMatrix
            vals = val.vals * d_row[..., :, None] * d_col[val.cols]
            return dataclasses.replace(val, vals=vals)
        return val * d_row[..., :, None] * d_col
    raise ValueError(f"unknown qp field: {name}")


def as_scaled_arrays(scaling, dtype):
    """(d_row, d_col) of a boxqp.Scaling as working-dtype jnp arrays —
    the shared conversion point of the template-scaling contract."""
    d_row = jnp.asarray(np.asarray(scaling.d_row), dtype)
    d_col = jnp.asarray(np.asarray(scaling.d_col), dtype)
    return d_row, d_col


def from_specs(specs: list[ScenarioSpec],
               tree: ScenarioTree | None = None,
               dtype=jnp.float32,
               scale: bool = True,
               scaling=None) -> ScenarioBatch:
    """Stack scenario specs into a device batch (the scenario compiler).

    scaling: a precomputed SHARED boxqp.Scaling (the scengen template-
    scaling path, docs/scengen.md): Ruiz equilibration is skipped and
    the given (d_row, d_col) are applied via scale_field's dtype-first
    f32 arithmetic — bit-identical to what VirtualBatch.realize
    synthesizes on device from the same ScenarioProgram."""
    if not specs:
        raise ValueError("need at least one scenario")
    n = specs[0].c.shape[0]
    nonant_idx = np.asarray(specs[0].nonant_idx, np.int32)
    for sp in specs:
        if sp.c.shape[0] != n or not np.array_equal(
                np.asarray(sp.nonant_idx, np.int32), nonant_idx):
            raise ValueError(f"scenario {sp.name}: inconsistent layout")

    if tree is None:
        tree = two_stage_tree(len(specs), len(nonant_idx))
    if tree.num_nonant_slots != len(nonant_idx):
        raise ValueError("nonant_idx length does not match tree slots")
    if tree.num_scenarios != len(specs):
        raise ValueError("scenario count does not match tree")

    probs = np.array([1.0 / len(specs) if sp.probability is None
                      else sp.probability for sp in specs])
    if not np.isclose(probs.sum(), 1.0, atol=1e-6):
        # same check as ref:mpisppy/spbase.py:461-506
        raise ValueError(f"scenario probabilities sum to {probs.sum()}")

    def stack(field):
        raw = [getattr(sp, field) for sp in specs]
        if all(a is raw[0] for a in raw[1:]):
            # identity fast path: generators share deterministic arrays
            return np.asarray(raw[0], np.float64)
        arrs = [np.asarray(a, np.float64) for a in raw]
        first = arrs[0]
        if all(a.shape == first.shape and np.array_equal(a, first)
               for a in arrs[1:]):
            return first  # shared across the batch (broadcasts)
        return np.stack(arrs)

    def stack_A():
        """Dense specs stack like any field; scipy-sparse specs become
        one EllMatrix (shared when deterministic, batched-values when
        only the data varies — the sparsity pattern must be shared)."""
        raw = [sp.A for sp in specs]
        import scipy.sparse as sps
        if not any(sps.issparse(a) for a in raw):
            return stack("A")
        from mpisppy_tpu.ops import sparse as sparse_mod
        if all(a is raw[0] for a in raw[1:]):
            return sparse_mod.ell_from_scipy(raw[0], dtype)
        # ell_from_scipy_batch itself collapses value-equal matrices to
        # a shared block (the sparse analog of stack()'s fallback)
        return sparse_mod.ell_from_scipy_batch(raw, dtype)

    A = stack_A()
    cones = None
    if any(sp.soc_blocks for sp in specs):
        from mpisppy_tpu.ops import cones as cones_mod
        blocks0 = [np.asarray(b, np.int64)
                   for b in (specs[0].soc_blocks or [])]
        for sp in specs[1:]:
            other = sp.soc_blocks or []
            if len(other) != len(blocks0) or not all(
                    np.array_equal(np.asarray(b, np.int64), b0)
                    for b, b0 in zip(other, blocks0)):
                raise ValueError(
                    f"scenario {sp.name}: SOC block pattern differs from "
                    "scenario 0's (the cone partition is shared across "
                    "the batch, like the nonant layout)")
        cones = cones_mod.cone_spec(specs[0].A.shape[0], blocks0)
        cones_mod.validate_against_bounds(cones, stack("bl"), stack("bu"))
    if scaling is not None:
        # scengen template-scaling path: fields go to the working dtype
        # FIRST, then scale via scale_field — the same f32 arithmetic
        # VirtualBatch.realize runs in-trace, so host materialization
        # and device synthesis bit-match.  c/q stay sharing-aware here
        # and broadcast to (S, n) (the kernel batch-shape contract).
        S = len(specs)
        raw_q = [sp.q for sp in specs]
        if all(r is None for r in raw_q):
            q_arr = np.zeros(n)
        else:
            q_arr = np.stack([np.zeros(n) if r is None
                              else np.asarray(r, np.float64)
                              for r in raw_q])
        d_row_j, d_col_j = as_scaled_arrays(scaling, dtype)

        def sf(name, arr):
            if not hasattr(arr, "vals"):
                arr = jnp.asarray(arr, dtype)
            return scale_field(name, arr, d_row_j, d_col_j)

        qp = BoxQP(
            c=jnp.broadcast_to(sf("c", stack("c")), (S, n)),
            q=jnp.broadcast_to(sf("q", q_arr), (S, n)),
            A=sf("A", A),
            bl=sf("bl", stack("bl")), bu=sf("bu", stack("bu")),
            l=sf("l", stack("l")), u=sf("u", stack("u")),
            cones=cones,
        )
    else:
        c = np.stack([np.asarray(sp.c, np.float64) for sp in specs])
        q = np.stack([np.zeros(n) if sp.q is None
                      else np.asarray(sp.q, np.float64) for sp in specs])
        qp = BoxQP(
            c=jnp.asarray(c, dtype), q=jnp.asarray(q, dtype),
            A=A if not isinstance(A, np.ndarray) else jnp.asarray(A, dtype),
            bl=jnp.asarray(stack("bl"), dtype),
            bu=jnp.asarray(stack("bu"), dtype),
            l=jnp.asarray(stack("l"), dtype),
            u=jnp.asarray(stack("u"), dtype),
            cones=cones,
        )
        if scale:
            qp, scaling = ruiz_scale(qp)
            d_col, d_row = scaling.d_col, scaling.d_row
        else:
            d_col = np.ones(A.shape[:-2] + (n,))
            d_row = np.ones(A.shape[:-1])
        d_col_j = jnp.asarray(d_col, dtype)
        d_row_j = jnp.asarray(d_row, dtype)

    integer = np.zeros(n, bool)
    if specs[0].integer is not None:
        integer = np.asarray(specs[0].integer, bool)

    var_prob = None
    if any(sp.var_prob is not None for sp in specs):
        # var_prob entries are ABSOLUTE per-(scenario, slot)
        # probabilities (they replace p in the reductions), so specs
        # without one default to their scenario probability —
        # the reference's prob_coeff-defaults-to-probability semantics
        # (ref:mpisppy/spbase.py:398-441)
        var_prob = jnp.asarray(np.stack([
            np.full(len(nonant_idx), probs[i]) if sp.var_prob is None
            else np.asarray(sp.var_prob, np.float64)
            for i, sp in enumerate(specs)]), dtype)

    return ScenarioBatch(
        var_prob=var_prob,
        qp=qp,
        d_col=d_col_j,
        d_row=d_row_j,
        d_non=d_col_j[..., nonant_idx] if d_col_j.ndim > 1
        else d_col_j[nonant_idx],
        p=jnp.asarray(probs, dtype),
        nonant_idx=jnp.asarray(nonant_idx),
        node_of_slot=jnp.asarray(tree.node_of_slot()),
        integer_slot=jnp.asarray(integer[nonant_idx]),
        integer_full=jnp.asarray(integer),
        tree=tree,
        num_real=len(specs),
    )


def pad_to_multiple(batch: ScenarioBatch, multiple: int) -> ScenarioBatch:
    """Pad the scenario axis so it divides the mesh size.  Padded rows
    duplicate the last scenario with probability 0, so every p-weighted
    reduction (xbar, bounds, convergence) is unchanged."""
    S = batch.num_scenarios
    pad = (-S) % multiple
    if pad == 0:
        return batch

    def pad_leading(x, batched_ndim):
        """Pad only fields that actually carry the scenario axis (shared
        fields are identified by ndim, not shape[0], so m==S or n==S
        cannot misfire).  ELL A pads its values; the pattern is shared."""
        if hasattr(x, "vals"):  # ops.sparse.EllMatrix
            return dataclasses.replace(
                x, vals=pad_leading(x.vals, batched_ndim))
        if x.ndim != batched_ndim:
            return x
        reps = jnp.repeat(x[-1:], pad, axis=0)
        return jnp.concatenate([x, reps], axis=0)

    qp = batch.qp
    qp = dataclasses.replace(
        qp,
        c=pad_leading(qp.c, 2), q=pad_leading(qp.q, 2),
        A=pad_leading(qp.A, 3),
        bl=pad_leading(qp.bl, 2), bu=pad_leading(qp.bu, 2),
        l=pad_leading(qp.l, 2), u=pad_leading(qp.u, 2),
    )
    var_prob = batch.var_prob
    if var_prob is not None:
        # padded rows get ZERO weights: var-prob reductions use the
        # weights directly (not p), so nonzero pads would enter the
        # node-average denominators
        var_prob = jnp.concatenate(
            [var_prob, jnp.zeros((pad,) + var_prob.shape[1:],
                                 var_prob.dtype)], axis=0)
    return dataclasses.replace(
        batch,
        qp=qp,
        d_col=pad_leading(batch.d_col, 2),
        d_row=pad_leading(batch.d_row, 2),
        d_non=pad_leading(batch.d_non, 2),
        p=jnp.concatenate([batch.p, jnp.zeros(pad, batch.p.dtype)]),
        node_of_slot=pad_leading(batch.node_of_slot, 2),
        var_prob=var_prob,
    )
