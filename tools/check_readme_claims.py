#!/usr/bin/env python
###############################################################################
# README perf-claim lint — THIN SHIM over the graftlint readme-claims
# pass (ISSUE 10: `python -m tools.graftlint` is the real runner; this
# entry point and its find_violations(readme=, pool=) surface are
# preserved for the existing tier-1 wiring).  Matching rules, units
# and the precision-disclosure check live in
# tools/graftlint/rules_readme_claims.py.
###############################################################################
from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.graftlint.rules_readme_claims import (  # noqa: E402,F401
    APPROX_REL_TOL, CLAIM_RE, PRECISION_TOKENS, SECTION_END,
    SECTION_START, SPEED_UNITS, UNITS, _matches, artifact_pool,
    check_readme, claims_in, undisclosed_precision_bullets,
)

README = os.path.join(_REPO, "README.md")


def find_violations(readme: str = README,
                    pool: set | None = None) -> list[str]:
    """Back-compat surface: violation strings in the pre-graftlint
    format."""
    if pool is None:
        pool = artifact_pool(_REPO)
    return [f"{os.path.basename(readme)}: {f.message}"
            for f in check_readme(readme, pool)]


def main() -> int:
    violations = find_violations()
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} unwitnessed perf claim(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
