#!/usr/bin/env python
###############################################################################
# No-bare-print lint — THIN SHIM over the graftlint no-print pass
# (ISSUE 10: `python -m tools.graftlint` is the real runner; this
# entry point and its find_violations() surface are preserved for the
# existing tier-1 wiring and muscle memory).  Rule doc, allowlist and
# marker live in tools/graftlint/rules_no_print.py.
###############################################################################
from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.graftlint.core import Context  # noqa: E402
from tools.graftlint.rules_no_print import (  # noqa: E402,F401
    ALLOWED_FILES, MARKER, PRINT_RE, RULE,
)

LIB_ROOT = os.path.join(_REPO, "mpisppy_tpu")


def find_violations(root: str = LIB_ROOT) -> list[str]:
    """Back-compat surface: violation strings, same format as the
    pre-graftlint tool (rel-to-lib paths)."""
    repo = os.path.dirname(root)
    ctx = Context(repo, paths=[root],
                  lib_dir=os.path.basename(root))
    out = []
    for f in RULE.run(ctx):
        if ctx.suppressed(f.path, f.line, f.rule):
            continue
        rel = os.path.relpath(os.path.join(repo, f.path),
                              root).replace(os.sep, "/")
        out.append(f"{rel}:{f.line}: {f.message}")
    return out


def main() -> int:
    violations = find_violations()
    for v in violations:
        print(v)  # the lint tool itself is not library code
    if violations:
        print(f"{len(violations)} bare print(s) in library code")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
