#!/usr/bin/env python
###############################################################################
# No-bare-print lint (ISSUE 3 satellite; enforced in tier-1 by
# tests/test_telemetry.py::test_no_bare_prints_in_library_code).
#
# Library code must report through the telemetry console
# (mpisppy_tpu.telemetry.console.log) so every human-readable line is
# verbosity-filtered and lands in the JSONL trace; a bare `print(` is
# invisible to both.  Allowed exceptions:
#
#   * the console/sink implementations themselves,
#   * __main__ / dryrun entry points (their stdout IS the product),
#   * lines carrying a `# telemetry: allow-print` marker — the CLI's
#     machine-readable JSON result protocol on stdout/stderr.
###############################################################################
from __future__ import annotations

import os
import re
import sys

LIB_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "mpisppy_tpu")

ALLOWED_FILES = {
    "telemetry/console.py",   # the console sink of last resort
    "telemetry/sinks.py",     # ConsoleSink rendering
    "telemetry/__main__.py",  # trace-toolbox CLI (its stdout IS the
                              # product: reports + JSON)
    "telemetry/watch.py",     # live-monitor renderer (stdout IS the
                              # product: the refreshing status block)
    "__main__.py",            # CLI entry point
    "parallel/_multihost_dryrun.py",  # multihost smoke entry point
    "confidence_intervals/mmw_conf.py",  # CLI entry point (JSON stdout)
    "resilience/watchdog.py",  # abort-path last words go straight to
                               # stderr: the telemetry console may be
                               # wedged inside the very stall the
                               # watchdog is escaping (ISSUE 9)
}

MARKER = "telemetry: allow-print"
PRINT_RE = re.compile(r"(?<![\w.])print\(")


def find_violations(root: str = LIB_ROOT) -> list[str]:
    violations = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel in ALLOWED_FILES:
                continue
            with open(path) as f:
                for lineno, line in enumerate(f, 1):
                    # match only the code portion: a print( mentioned in
                    # a comment (or the allow marker itself) is fine
                    code = line.split("#", 1)[0]
                    if PRINT_RE.search(code) and MARKER not in line:
                        violations.append(
                            f"{rel}:{lineno}: bare print( — use "
                            f"mpisppy_tpu.telemetry.console.log "
                            f"(or add `# {MARKER}` for CLI protocol "
                            f"output)")
    return violations


def main() -> int:
    violations = find_violations()
    for v in violations:
        print(v)  # the lint tool itself is not library code
    if violations:
        print(f"{len(violations)} bare print(s) in library code")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
