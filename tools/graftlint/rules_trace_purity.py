###############################################################################
# trace-purity: the PR-4 recompile-leak class, caught at lint time.
#
# `lax.fori_loop`/`while_loop`/`scan`/`cond`/`switch` called EAGERLY
# (outside any jit trace) traces its body with every closed-over array
# baked in as a jaxpr CONSTANT — XLA compiles a fresh loop executable
# per distinct operand VALUES, one silent backend compile per call.
# That is exactly the pair of leaks the runtime compile-guard found
# after PR 4 shipped (ops/pdhg.estimate_norm, ops/bnb._solve_node);
# this pass flags the whole class before runtime.
#
# Analysis (per module, AST only — documented approximation):
#   * a function is JIT-PROTECTED when it is decorated with jax.jit /
#     partial(jax.jit, ...) / pl.pallas_call-style kernels, when its
#     name contains "_jit" (the repo convention for trace-only
#     helpers), or when it is nested inside a protected function;
#   * a PRIVATE top-level function (leading underscore) inherits
#     protection when every intra-module caller is protected (fixed
#     point over the module call graph) — e.g. simplex_qp._estimate_L
#     is only reachable through the jitted solve_simplex_qp;
#   * a lax control-flow call site whose outermost enclosing function
#     is unprotected (or that sits at module level) is a finding.
#     Public functions are assumed host-callable: an eager entry point
#     that owns a lax loop must either jit it (shape-keyed) or carry a
#     justification (inline allow or baseline entry).
#
# Second check, same bug family: `jax.jit(<lambda or local def>)`
# CONSTRUCTED inside a function body builds a fresh jitted callable —
# and a fresh compile cache — per call; the jit cache keys on the
# wrapper object, so every invocation recompiles.  Module-level /
# decorator jits are fine.
###############################################################################
from __future__ import annotations

import ast
import re

from tools.graftlint.core import Context, Finding, Rule

RULE_NAME = "trace-purity"
CONTROL_FLOW = {"fori_loop", "while_loop", "scan", "cond", "switch"}

_JIT_DEC_RE = re.compile(r"(^|[.(\s])jit\b")


def _dec_is_jit(dec: ast.expr) -> bool:
    """jax.jit / jit / partial(jax.jit, ...) / functools.partial(jit)."""
    return bool(_JIT_DEC_RE.search(ast.unparse(dec)))


def _is_lax_cf(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in CONTROL_FLOW:
        chain = ast.unparse(f.value)
        if chain.endswith("lax"):
            return f.attr
    return None


class _FnInfo:
    __slots__ = ("name", "node", "protected", "private", "calls",
                 "cf_sites", "jit_closures", "cls")

    def __init__(self, name, node, cls: str | None = None):
        self.name = name
        self.node = node
        self.cls = cls                     # owning class (methods)
        self.protected = False
        self.private = name.split(".")[-1].startswith("_")
        self.calls: set[str] = set()       # referenced callable names
        self.cf_sites: list[tuple[int, str]] = []
        self.jit_closures: list[tuple[int, str]] = []


def _analyze_module(tree: ast.Module):
    """Top-level function table + module-level control-flow sites."""
    fns: dict[str, _FnInfo] = {}
    module_sites: list[tuple[int, str]] = []

    def scan_body(fn: _FnInfo | None, node: ast.AST,
                  protected: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_protected = protected \
                    or any(_dec_is_jit(d) for d in child.decorator_list) \
                    or "_jit" in child.name
                scan_body(fn, child, child_protected)
                continue
            if isinstance(child, ast.Call):
                kind = _is_lax_cf(child)
                if kind is not None and not protected:
                    site = (child.lineno, kind)
                    (fn.cf_sites if fn else module_sites).append(site)
                # jit(<lambda/local def>) built inside a function body
                if fn is not None:
                    ftxt = ast.unparse(child.func)
                    if ftxt.endswith("jit") and child.args and isinstance(
                            child.args[0], ast.Lambda):
                        fn.jit_closures.append(
                            (child.lineno, "jit(lambda)"))
            if isinstance(child, ast.Name) and fn is not None:
                fn.calls.add(child.id)
            # self._helper(...) references register class-qualified so
            # the protection fixed point also covers private METHODS
            # reachable only through a jitted sibling method
            if isinstance(child, ast.Attribute) and fn is not None \
                    and fn.cls is not None \
                    and isinstance(child.value, ast.Name) \
                    and child.value.id == "self":
                fn.calls.add(f"{fn.cls}.{child.attr}")
            scan_body(fn, child, protected)

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = _FnInfo(node.name, node)
            info.protected = any(_dec_is_jit(d)
                                 for d in node.decorator_list) \
                or "_jit" in node.name
            fns[node.name] = info
        elif isinstance(node, ast.ClassDef):
            # methods: treated like top-level functions qualified by
            # class (no cross-class call-graph; jit decoration and
            # _jit naming still protect, and self.-calls feed the
            # fixed point above)
            for b in node.body:
                if isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info = _FnInfo(f"{node.name}.{b.name}", b,
                                   cls=node.name)
                    info.protected = any(_dec_is_jit(d)
                                         for d in b.decorator_list) \
                        or "_jit" in b.name
                    fns[info.name] = info

    for info in fns.values():
        scan_body(info, info.node, info.protected)
    # module-level statements (outside any def)
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    kind = _is_lax_cf(sub)
                    if kind is not None:
                        module_sites.append((sub.lineno, kind))

    # fixed point: a private function whose every intra-module caller
    # is protected inherits protection
    callers: dict[str, set[str]] = {n: set() for n in fns}
    for name, info in fns.items():
        for callee in info.calls:
            if callee in fns:
                callers[callee].add(name)
    changed = True
    while changed:
        changed = False
        for name, info in fns.items():
            if info.protected or not info.private:
                continue
            cs = callers[name] - {name}
            if cs and all(fns[c].protected for c in cs):
                info.protected = True
                changed = True
    return fns, module_sites


def run(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    for rel in ctx.files:
        try:
            tree = ctx.tree(rel)
        except SyntaxError:
            continue
        fns, module_sites = _analyze_module(tree)
        for line, kind in module_sites:
            out.append(Finding(
                RULE_NAME, rel, line,
                f"eager lax.{kind} at module level — traces with "
                f"operand values baked in; wrap in a jitted function",
                key=f"{rel}::<module>::{kind}"))
        for info in fns.values():
            if info.protected:
                continue
            for line, kind in info.cf_sites:
                out.append(Finding(
                    RULE_NAME, rel, line,
                    f"lax.{kind} reachable eagerly via {info.name}() — "
                    f"closed-over arrays become jaxpr constants and "
                    f"every distinct input VALUE recompiles (the PR-4 "
                    f"leak class); jit the call site shape-keyed "
                    f"(@jax.jit or a *_jit helper)",
                    key=f"{rel}::{info.name}::{kind}"))
            for line, what in info.jit_closures:
                out.append(Finding(
                    RULE_NAME, rel, line,
                    f"{what} constructed per call inside {info.name}() "
                    f"— a fresh jit wrapper (and compile-cache entry) "
                    f"every invocation; hoist the jitted callable to "
                    f"module scope",
                    key=f"{rel}::{info.name}::{what}"))
    return out


RULE = Rule(RULE_NAME,
            "eager lax control flow / per-call jit wrappers "
            "(recompile-leak class)", run)
