###############################################################################
# trace-purity: the PR-4 recompile-leak class, caught at lint time.
#
# `lax.fori_loop`/`while_loop`/`scan`/`cond`/`switch` called EAGERLY
# (outside any jit trace) traces its body with every closed-over array
# baked in as a jaxpr CONSTANT — XLA compiles a fresh loop executable
# per distinct operand VALUES, one silent backend compile per call.
# That is exactly the pair of leaks the runtime compile-guard found
# after PR 4 shipped (ops/pdhg.estimate_norm, ops/bnb._solve_node);
# this pass flags the whole class before runtime.
#
# Analysis (per module, AST only — documented approximation):
#   * a function is JIT-PROTECTED when it is decorated with jax.jit /
#     partial(jax.jit, ...) / pl.pallas_call-style kernels, when its
#     name contains "_jit" (the repo convention for trace-only
#     helpers), when it is nested inside a protected function, when a
#     MODULE-LEVEL assignment wraps it (`g = jax.jit(f)` /
#     `g = partial(jax.jit, ...)(f)` — the wrapper counts as one
#     protected CALLER in the fixed point, so an additional eager call
#     path to f still flags), or when it is decorated with a
#     module-level jit ALIAS (`_jit = partial(jax.jit,
#     static_argnames=...)` then `@_jit` — the decorator-aliased
#     form);
#   * a PRIVATE top-level function (leading underscore) inherits
#     protection when every intra-module caller is protected (fixed
#     point over the module call graph) — e.g. simplex_qp._estimate_L
#     is only reachable through the jitted solve_simplex_qp;
#   * a lax control-flow call site whose outermost enclosing function
#     is unprotected (or that sits at module level) is a finding.
#     Public functions are assumed host-callable: an eager entry point
#     that owns a lax loop must either jit it (shape-keyed) or carry a
#     justification (inline allow or baseline entry).
#
# Second check, same bug family: `jax.jit(<lambda or local def>)`
# CONSTRUCTED inside a function body builds a fresh jitted callable —
# and a fresh compile cache — per call; the jit cache keys on the
# wrapper object, so every invocation recompiles.  Module-level /
# decorator jits are fine.
###############################################################################
from __future__ import annotations

import ast
import re

from tools.graftlint.core import Context, Finding, Rule

RULE_NAME = "trace-purity"
CONTROL_FLOW = {"fori_loop", "while_loop", "scan", "cond", "switch"}

_JIT_DEC_RE = re.compile(r"(^|[.(\s])jit\b")


def _dec_is_jit(dec: ast.expr, aliases: frozenset = frozenset()) -> bool:
    """jax.jit / jit / partial(jax.jit, ...) / functools.partial(jit),
    or a module-level alias of one of those (`@_jit`, `@_jit(...)`)."""
    if _JIT_DEC_RE.search(ast.unparse(dec)):
        return True
    if isinstance(dec, ast.Name) and dec.id in aliases:
        return True
    if isinstance(dec, ast.Call) and isinstance(dec.func, ast.Name) \
            and dec.func.id in aliases:
        return True
    return False


def _jit_aliases(tree: ast.Module) -> frozenset:
    """Module-level names bound to a jit DECORATOR FACTORY:
    `_jit = jax.jit` or `_jit = partial(jax.jit, static_argnames=...)`
    (the value mentions jit but does not yet APPLY it to a function —
    that's the wrapped-assignment case below)."""
    out = set()
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        val = node.value
        if isinstance(val, (ast.Attribute, ast.Name)) \
                and _JIT_DEC_RE.search(ast.unparse(val)):
            out.add(node.targets[0].id)
        elif isinstance(val, ast.Call) \
                and ast.unparse(val.func).split(".")[-1] == "partial" \
                and any(_JIT_DEC_RE.search(ast.unparse(a))
                        for a in val.args):
            out.add(node.targets[0].id)
    return frozenset(out)


def _wrapped_protected(tree: ast.Module, aliases: frozenset) -> set:
    """Function names WRAPPED by a module-level jit assignment:
    `g = jax.jit(f, ...)`, `g = partial(jax.jit, ...)(f)`,
    `g = _jit(f)`.  A wrapped name is NOT unconditionally protected —
    the wrapper counts as one protected CALLER in the fixed point, so
    f still gets flagged when some other intra-module caller reaches
    it eagerly (a direct f() call outside any jit is exactly the PR-4
    leak the wrapping was supposed to prevent)."""
    out: set[str] = set()
    for node in tree.body:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        func_txt = ast.unparse(call.func)
        is_jit = bool(_JIT_DEC_RE.search(func_txt)) \
            or (isinstance(call.func, ast.Name)
                and call.func.id in aliases)
        if is_jit and call.args and isinstance(call.args[0], ast.Name):
            out.add(call.args[0].id)
    return out


def _is_lax_cf(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in CONTROL_FLOW:
        chain = ast.unparse(f.value)
        if chain.endswith("lax"):
            return f.attr
    return None


class _FnInfo:
    __slots__ = ("name", "node", "protected", "private", "calls",
                 "cf_sites", "jit_closures", "cls")

    def __init__(self, name, node, cls: str | None = None):
        self.name = name
        self.node = node
        self.cls = cls                     # owning class (methods)
        self.protected = False
        self.private = name.split(".")[-1].startswith("_")
        self.calls: set[str] = set()       # referenced callable names
        self.cf_sites: list[tuple[int, str]] = []
        self.jit_closures: list[tuple[int, str]] = []


def _analyze_module(tree: ast.Module):
    """Top-level function table + module-level control-flow sites."""
    fns: dict[str, _FnInfo] = {}
    module_sites: list[tuple[int, str]] = []
    aliases = _jit_aliases(tree)
    wrapped = _wrapped_protected(tree, aliases)

    def scan_body(fn: _FnInfo | None, node: ast.AST,
                  protected: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_protected = protected \
                    or any(_dec_is_jit(d, aliases)
                           for d in child.decorator_list) \
                    or "_jit" in child.name
                scan_body(fn, child, child_protected)
                continue
            if isinstance(child, ast.Call):
                kind = _is_lax_cf(child)
                if kind is not None and not protected:
                    site = (child.lineno, kind)
                    (fn.cf_sites if fn else module_sites).append(site)
                # jit(<lambda/local def>) built inside a function body
                if fn is not None:
                    ftxt = ast.unparse(child.func)
                    if ftxt.endswith("jit") and child.args and isinstance(
                            child.args[0], ast.Lambda):
                        fn.jit_closures.append(
                            (child.lineno, "jit(lambda)"))
            if isinstance(child, ast.Name) and fn is not None:
                fn.calls.add(child.id)
            # self._helper(...) references register class-qualified so
            # the protection fixed point also covers private METHODS
            # reachable only through a jitted sibling method
            if isinstance(child, ast.Attribute) and fn is not None \
                    and fn.cls is not None \
                    and isinstance(child.value, ast.Name) \
                    and child.value.id == "self":
                fn.calls.add(f"{fn.cls}.{child.attr}")
            scan_body(fn, child, protected)

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = _FnInfo(node.name, node)
            info.protected = any(_dec_is_jit(d, aliases)
                                 for d in node.decorator_list) \
                or "_jit" in node.name
            fns[node.name] = info
        elif isinstance(node, ast.ClassDef):
            # methods: treated like top-level functions qualified by
            # class (no cross-class call-graph; jit decoration and
            # _jit naming still protect, and self.-calls feed the
            # fixed point above)
            for b in node.body:
                if isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info = _FnInfo(f"{node.name}.{b.name}", b,
                                   cls=node.name)
                    info.protected = any(_dec_is_jit(d, aliases)
                                         for d in b.decorator_list) \
                        or "_jit" in b.name
                    fns[info.name] = info

    for info in fns.values():
        scan_body(info, info.node, info.protected)
    # module-level statements (outside any def)
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    kind = _is_lax_cf(sub)
                    if kind is not None:
                        module_sites.append((sub.lineno, kind))

    # fixed point: a private (or module-level jit-WRAPPED) function
    # whose every intra-module caller is protected inherits protection.
    # The wrapping assignment itself counts as one protected caller —
    # so `g = jax.jit(f)` protects f, but a second, eager f() call
    # site keeps it flagged.
    callers: dict[str, set[str]] = {n: set() for n in fns}
    for name, info in fns.items():
        for callee in info.calls:
            if callee in fns:
                callers[callee].add(name)
    _WRAP = "<module-jit-wrap>"
    wrap_info = _FnInfo(_WRAP, None)
    wrap_info.protected = True
    fns[_WRAP] = wrap_info
    for name in wrapped:
        if name in callers:
            callers[name].add(_WRAP)
    changed = True
    while changed:
        changed = False
        for name, info in fns.items():
            if info.protected or not (info.private or name in wrapped):
                continue
            cs = callers.get(name, set()) - {name}
            if cs and all(fns[c].protected for c in cs):
                info.protected = True
                changed = True
    del fns[_WRAP]
    return fns, module_sites


def run(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    for rel in ctx.files:
        try:
            tree = ctx.tree(rel)
        except SyntaxError:
            continue
        fns, module_sites = _analyze_module(tree)
        for line, kind in module_sites:
            out.append(Finding(
                RULE_NAME, rel, line,
                f"eager lax.{kind} at module level — traces with "
                f"operand values baked in; wrap in a jitted function",
                key=f"{rel}::<module>::{kind}"))
        for info in fns.values():
            if info.protected:
                continue
            for line, kind in info.cf_sites:
                out.append(Finding(
                    RULE_NAME, rel, line,
                    f"lax.{kind} reachable eagerly via {info.name}() — "
                    f"closed-over arrays become jaxpr constants and "
                    f"every distinct input VALUE recompiles (the PR-4 "
                    f"leak class); jit the call site shape-keyed "
                    f"(@jax.jit or a *_jit helper)",
                    key=f"{rel}::{info.name}::{kind}"))
            for line, what in info.jit_closures:
                out.append(Finding(
                    RULE_NAME, rel, line,
                    f"{what} constructed per call inside {info.name}() "
                    f"— a fresh jit wrapper (and compile-cache entry) "
                    f"every invocation; hoist the jitted callable to "
                    f"module scope",
                    key=f"{rel}::{info.name}::{what}"))
    return out


RULE = Rule(RULE_NAME,
            "eager lax control flow / per-call jit wrappers "
            "(recompile-leak class)", run)
