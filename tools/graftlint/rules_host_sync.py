###############################################################################
# host-sync: device-to-host synchronization inside the ops/ hot path.
#
# `.item()`, `float()/int()/bool()` coercions, `np.asarray(...)` and
# `.block_until_ready()` on a traced/device value force a blocking
# device->host transfer.  Inside the ops/ kernels — the code the wheel
# dispatches thousands of times per run — a stray sync serializes the
# pipeline (and, under jit, raises TracerError at the worst possible
# time: on the first caller who composes the op into a larger trace).
#
# Scope: the ITERATION KERNELS (pdhg, pdhg_pallas, simplex_qp) — the
# modules whose bodies run inside the wheel's per-iteration dispatch,
# where a stray sync serializes every restart window.  The rest of
# ops/ is host-boundary by design and exempt: bnb.py is the host-side
# B&B orchestrator (its np.asarray calls ARE the harvest), and
# boxqp/cones/fbbt/sparse mix trace-pure kernels with problem
# CONSTRUCTION and certificate RENDERING helpers that legitimately
# materialize host values once per problem, not per iteration.
# Legitimate syncs inside a hot module (the documented host seams,
# e.g. pdhg.solve's auto-chunk loop reading st.k between capped
# dispatches) carry an inline `# graftlint: allow-host-sync`.
#
# Coercion heuristic: float()/int()/bool() are flagged only when the
# argument expression mentions a jnp/jax value or an attribute chain
# (e.g. `int(st.k)`, `bool(jnp.all(...))`) — `int(opts.max_iters)` on
# a plain Python options field is noise, and `float("inf")` /
# `int(3)` literals never sync.
###############################################################################
from __future__ import annotations

import ast

from tools.graftlint.core import Context, Finding, Rule

RULE_NAME = "host-sync"

#: ops/ modules that must stay pure-trace end to end; the rest of
#: ops/ is host-boundary by design (see module header)
HOT_MODULES = ("ops/pdhg.py", "ops/pdhg_pallas.py",
               "ops/simplex_qp.py")

_COERCIONS = {"float", "int", "bool"}


def _mentions_device_value(node: ast.expr) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in ("jnp", "jax", "lax"):
            return True
        if isinstance(sub, ast.Attribute):
            return True
    return False


def _scan(ctx: Context, rel: str) -> list[Finding]:
    out: list[Finding] = []
    try:
        tree = ctx.tree(rel)
    except SyntaxError:
        return out

    # enclosing-function map: content-based baseline keys
    # (fn::construct::occurrence), never raw line windows — a line
    # bucket would let one grandfathered entry cover a FUTURE
    # violation landing nearby
    owner: dict[int, str] = {}
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    owner[id(sub)] = fn.name   # innermost wins (walk
                    # order visits outer defs first, inner later)
    counts: dict[tuple[str, str], int] = {}

    def add(node, what, hint):
        fn_name = owner.get(id(node), "<module>")
        n = counts[(fn_name, what)] = counts.get((fn_name, what), 0) + 1
        out.append(Finding(
            RULE_NAME, rel, node.lineno,
            f"{what} in a hot ops/ module forces a device->host sync "
            f"({hint})",
            key=f"{rel}::{fn_name}::{what}::{n}"))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "item" and not node.args:
                add(node, ".item()", "transfer + blocks the pipeline")
            elif f.attr == "block_until_ready":
                add(node, ".block_until_ready()",
                    "blocks the dispatch pipeline")
            elif f.attr == "asarray" and isinstance(f.value, ast.Name) \
                    and f.value.id in ("np", "numpy"):
                add(node, "np.asarray(...)",
                    "materializes the device value on host; use jnp "
                    "inside kernels, or move the harvest to the "
                    "orchestrator layer")
        elif isinstance(f, ast.Name) and f.id in _COERCIONS \
                and len(node.args) == 1 \
                and _mentions_device_value(node.args[0]):
            add(node, f"{f.id}(...) coercion",
                "scalar coercion of a (likely) device value; keep it "
                "an array, or mark the documented host seam with "
                "`# graftlint: allow-host-sync`")
    return out


def run(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    lib = ctx.lib_dir
    targets = {f"{lib}/{m}" for m in HOT_MODULES}
    for rel in ctx.files:
        if rel in targets or any(rel.endswith("/" + m) or rel == m
                                 for m in HOT_MODULES):
            out.extend(_scan(ctx, rel))
    return out


RULE = Rule(RULE_NAME,
            "device->host syncs (.item/np.asarray/coercions) inside "
            "pure-trace ops modules", run)
