###############################################################################
# config-knob: every `cfg.<name>` read in library code must be a
# DECLARED knob, and knobs declared in utils/config.py's canned groups
# that nothing ever reads are dead weight (they parse, they show in
# --help, they do nothing — the worst kind of lie a CLI can tell).
#
# Declarations: literal first args of add_to_config / quick_assign /
# add_and_assign anywhere in the library (utils/config.py canned
# groups, the models' inparser_adders, confidence_config groups).
#
# Reads: `cfg.get("x")`, `cfg["x"]`, and `cfg.x` attribute access
# (receivers whose source text ends in `cfg`; Config API method names
# — parsed from the Config class itself — are excluded).  Because the
# hub wiring reads knob blocks via literal name tuples
# (`for key in ("checkpoint_path", ...): cfg.get(key)`), any string
# literal in library code equal to a declared knob name also counts
# as a READ REFERENCE for deadness purposes — the dead-knob check
# therefore proves "no module outside utils/config.py even MENTIONS
# the name", which is as close to unread as static analysis gets.
#
# An intentionally parse-only knob (a legacy alias kept so reference
# scripts keep parsing) carries `# graftlint: allow-config-knob` on
# its declaration line.
###############################################################################
from __future__ import annotations

import ast

from tools.graftlint.core import Context, Finding, Rule

RULE_NAME = "config-knob"

_DECL_METHODS = {"add_to_config", "quick_assign", "add_and_assign"}


def _config_api(ctx: Context) -> set[str]:
    """Method names of the Config class (excluded from attribute-read
    detection)."""
    rel = f"{ctx.lib_dir}/utils/config.py"
    api: set[str] = set()
    try:
        tree = ctx.tree(rel)
    except (OSError, SyntaxError):
        return api
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            for b in node.body:
                if isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    api.add(b.name)
    return api


def collect(ctx: Context):
    """(declared: name -> [(rel, line)], utils_declared: name ->
    (rel, line), reads: name -> [(rel, line)], mentions: set[str])."""
    declared: dict[str, list] = {}
    utils_declared: dict[str, tuple] = {}
    reads: dict[str, list] = {}
    literals: dict[str, list] = {}     # string literals outside config.py
    api = _config_api(ctx)
    cfg_rel = f"{ctx.lib_dir}/utils/config.py"
    for rel in ctx.files:
        try:
            tree = ctx.tree(rel)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                recv = ast.unparse(node.func.value)
                is_cfg = recv.endswith("cfg") or recv in ("config", "self")
                if attr in _DECL_METHODS and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    name = node.args[0].value
                    declared.setdefault(name, []).append(
                        (rel, node.lineno))
                    if rel == cfg_rel:
                        utils_declared.setdefault(
                            name, (rel, node.lineno))
                    continue
                if attr == "get" and is_cfg and recv != "self" \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    reads.setdefault(node.args[0].value, []).append(
                        (rel, node.lineno))
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str) \
                    and ast.unparse(node.value).endswith("cfg"):
                reads.setdefault(node.slice.value, []).append(
                    (rel, node.lineno))
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "cfg" \
                    and node.attr not in api \
                    and not node.attr.startswith("_"):
                reads.setdefault(node.attr, []).append((rel, node.lineno))
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) and rel != cfg_rel:
                literals.setdefault(node.value, []).append(
                    (rel, node.lineno))
    return declared, utils_declared, reads, literals


def run(ctx: Context) -> list[Finding]:
    declared, utils_declared, reads, literals = collect(ctx)
    out: list[Finding] = []
    for name, sites in sorted(reads.items()):
        if name in declared:
            continue
        for rel, line in sites:
            out.append(Finding(
                RULE_NAME, rel, line,
                f"cfg read of undeclared knob {name!r} — declare it in "
                f"a utils/config.py args group (argparse=False for "
                f"programmatic-only knobs) so --help, defaults and "
                f"this lint know it exists",
                key=f"{rel}::undeclared::{name}"))
    for name, (rel, line) in sorted(utils_declared.items()):
        if name in reads or name in literals:
            continue
        out.append(Finding(
            RULE_NAME, rel, line,
            f"declared knob {name!r} is never read (no cfg.get/"
            f"cfg[...]/attribute read, and no other module mentions "
            f"the name) — dead CLI surface; delete it or mark an "
            f"intentional parse-only alias with "
            f"`# graftlint: allow-config-knob`",
            key=f"dead::{name}"))
    return out


RULE = Rule(RULE_NAME,
            "undeclared cfg reads + dead (never-read) declared knobs",
            run)
