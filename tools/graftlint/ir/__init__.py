###############################################################################
# graftlint IR layer (ISSUE 15; docs/static_analysis.md "IR layer").
#
# A second analysis plane under the AST rules: every hot kernel in the
# manifest (manifest.py) is abstractly lowered on small shapes and its
# jaxpr/HLO facts are linted by five passes (passes.py) — const
# capture, dtype census, host boundary, collective manifest, memory
# high-water — with the per-kernel numbers committed as KERNEL_IR.json
# and ratcheted by telemetry/regress.py GATES.
#
# Importing this package stays jax-free (manifest/passes import
# lazily); the audit itself (audit.py) is the one sanctioned place the
# lint executes the code it judges — abstract lowering IS the analysis.
###############################################################################
from __future__ import annotations

from tools.graftlint.ir import manifest  # noqa: F401 (re-export)
from tools.graftlint.ir.passes import (  # noqa: F401 (re-exports)
    IR_RULES, kernel_counts, set_subset,
)
