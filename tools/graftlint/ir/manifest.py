###############################################################################
# graftlint IR layer: the declarative KERNEL MANIFEST (ISSUE 15 tentpole).
#
# Every jitted entry point the wheel stack dispatches in anger is
# enumerated here once, with a builder that constructs the kernel on
# SMALL abstract shapes through the same fixture machinery the driver
# dry run uses (__graft_entry__._flagship_batch/_sslp_batch/
# _bnb_probe_state/_cross_scen_probe_impl) — so the manifest and
# `dryrun_multichip` can never drift: they trace the same code through
# the same builders, and the dry run's collective asserts read THIS
# file's per-kernel declarations (declared_collectives) instead of
# hard-coding them.
#
# A KernelSpec is pure data + a lazy builder; importing this module
# costs nothing (no jax import at module scope) so the CLI can print
# per-rule kernel counts on a jax-less host.  The IR audit
# (tools/graftlint/ir/audit.py) calls spec.build(fx) to get
# (jitted_fn, args) and derives per-kernel facts from the jaxpr and the
# CPU-lowered HLO; the five IR passes (passes.py) lint those facts.
#
# Declaring a new kernel (docs/static_analysis.md, "IR layer"):
#   1. write a builder `fx -> (fn, args)` below (reuse the Fixtures
#      batches; keep shapes small — the audit is about IR structure,
#      not numerics);
#   2. append a KernelSpec: `sharded=True` + `collectives={...}` when
#      the kernel is dispatched against sharded batches (EXACT set —
#      the collective-manifest pass checks both directions),
#      `virtual=True` + `temp_budget_bytes` when it is VirtualBatch-fed
#      (the scengen "data exists only as transients" contract),
#      `fast=True` when it belongs in the tier-1 subset (cheap trace +
#      compile);
#   3. regenerate KERNEL_IR.json: `python -m tools.graftlint.ir
#      --emit KERNEL_IR.json`.
###############################################################################
from __future__ import annotations

import dataclasses
import functools

#: collective HLO ops the collective-manifest pass recognizes — the
#: kinds XLA SPMD partitioning can emit for our reductions/gathers
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")

#: bytes threshold for the const-capture pass: a concrete array
#: constant at least this large baked into a kernel's jaxpr is a
#: finding (the PR-4/PR-9 recompile-leak class; small iota/eye-style
#: constants are idiomatic and exempt)
CONST_BYTES_THRESHOLD = 1024


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """One audited kernel: identity + lazy builder + declarations."""

    name: str
    build: object               # Fixtures -> (jitted_fn, args tuple)
    doc: str = ""
    #: EXACT collective kinds the sharded (>= 2 device) lowering must
    #: contain — both directions are linted.  Only read when `sharded`.
    collectives: frozenset = frozenset()
    sharded: bool = False
    #: VirtualBatch-fed kernel: the memory-high-water pass enforces the
    #: scengen transients contract against `temp_budget_bytes`
    virtual: bool = False
    #: ceiling on compiled temp bytes (memory_analysis high-water) for
    #: virtual kernels — a materialized S-major copy that outlives the
    #: realize() transient blows straight through it
    temp_budget_bytes: int | None = None
    #: member of the tier-1 fast subset (budget-asserted < 60 s total)
    fast: bool = False


# ---------------------------------------------------------------------------
# fixtures: small abstract-shape batches + derived states, shared
# across builders and cached per audit run.  `mesh` None = single
# device; a Mesh shards every batch (the collective facts path).
# ---------------------------------------------------------------------------
class Fixtures:
    """Lazily built, memoized kernel inputs on small shapes."""

    def __init__(self, mesh=None):
        self.mesh = mesh

    def _memo(fn):  # noqa: N805 — decorator, not a method
        name = fn.__name__

        @property
        @functools.wraps(fn)
        def wrapper(self):
            key = "_memo_" + name
            if not hasattr(self, key):
                setattr(self, key, fn(self))
            return getattr(self, key)
        return wrapper

    def _shard(self, batch):
        if self.mesh is None:
            return batch
        from mpisppy_tpu.parallel import mesh as mesh_mod
        return mesh_mod.shard_batch(batch, self.mesh)

    @_memo
    def farmer(self):
        import __graft_entry__ as ge
        from mpisppy_tpu.core import batch as batch_mod
        n_dev = 1 if self.mesh is None else self.mesh.devices.size
        b = ge._flagship_batch(num_scens=max(6, 2 * n_dev),
                               crops_multiplier=1)
        if self.mesh is not None:
            b = batch_mod.pad_to_multiple(b, n_dev)
        return self._shard(b)

    @_memo
    def sslp(self):
        import __graft_entry__ as ge
        n_dev = 1 if self.mesh is None else self.mesh.devices.size
        return self._shard(ge._sslp_batch(num_scens=max(4, 2 * n_dev)))

    @_memo
    def ph_opts(self):
        from mpisppy_tpu.algos import ph as ph_mod
        from mpisppy_tpu.ops import pdhg
        return ph_mod.PHOptions(
            subproblem_windows=2, iter0_windows=4,
            pdhg=pdhg.PDHGOptions(tol=1e-4, restart_period=10))

    @_memo
    def pdhg_opts(self):
        from mpisppy_tpu.ops import pdhg
        return pdhg.PDHGOptions(tol=1e-4, max_iters=40,
                                restart_period=10)

    @_memo
    def rho(self):
        import jax.numpy as jnp
        return jnp.ones(self.farmer.num_nonants, jnp.float32)

    @_memo
    def ph_state(self):
        from mpisppy_tpu.algos import ph as ph_mod
        st, _, _ = ph_mod.ph_iter0(self.farmer, self.rho, self.ph_opts)
        return st

    @_memo
    def wheel_opts(self):
        from mpisppy_tpu.algos import fused_wheel as fw
        return fw.FusedWheelOptions(lag_windows=2, xhat_windows=2,
                                    slam_windows=1, shuffle_windows=1)

    @_memo
    def fused_state(self):
        from mpisppy_tpu.algos import fused_wheel as fw
        fst, _, _ = fw.fused_iter0(self.farmer, self.rho, self.ph_opts,
                                   self.wheel_opts)
        return fst

    @_memo
    def shuffle_id(self):
        import jax.numpy as jnp
        return jnp.asarray(1, jnp.int32)

    @_memo
    def xhat_cand(self):
        from mpisppy_tpu.algos import fused_wheel as fw
        return fw._round_xbar(self.farmer, self.ph_state.xbar_nodes)

    @_memo
    def fwph_opts(self):
        from mpisppy_tpu.algos import fwph as fwph_mod
        return fwph_mod.FWPHOptions(fw_iter_limit=1, max_columns=4,
                                    iter0_windows=4, oracle_windows=2)

    @_memo
    def fwph_state(self):
        from mpisppy_tpu.algos import fwph as fwph_mod
        st, _, _ = fwph_mod.fwph_init(self.farmer, self.rho,
                                      self.fwph_opts)
        return st

    @_memo
    def bnb_opts(self):
        from mpisppy_tpu.ops import bnb as bnb_mod
        from mpisppy_tpu.ops import pdhg
        return bnb_mod.BnBOptions(
            max_rounds=1, pump_rounds=0,
            lp=pdhg.PDHGOptions(tol=1e-3, max_iters=200))

    @_memo
    def bnb_state(self):
        import __graft_entry__ as ge
        return ge._bnb_probe_state(self.sslp, self.bnb_opts)

    @_memo
    def virtual(self):
        from mpisppy_tpu import scengen
        from mpisppy_tpu.models import farmer as farmer_model
        n_dev = 1 if self.mesh is None else self.mesh.devices.size
        prog = farmer_model.scenario_program(max(8, 2 * n_dev), seed=0)
        vb = scengen.virtual_batch(prog, pad_to=n_dev)
        if self.mesh is not None:
            from mpisppy_tpu.parallel import mesh as mesh_mod
            vb = mesh_mod.shard_batch(vb, self.mesh)
        return vb

    @_memo
    def virtual_rho(self):
        import jax.numpy as jnp
        return jnp.ones(self.virtual.num_nonants, jnp.float32)

    @_memo
    def virtual_ph_state(self):
        from mpisppy_tpu.algos import ph as ph_mod
        st, _, _ = ph_mod.ph_iter0(self.virtual, self.virtual_rho,
                                   self.ph_opts)
        return st

    @_memo
    def pdhg_init(self):
        from mpisppy_tpu.ops import pdhg
        return pdhg.init_state(self.sslp.qp, self.pdhg_opts)

    # -- elastic mesh (ISSUE 17): the harvest kernels at the full and
    # the shrunk (survivor) topology.  Prime S so the pad count
    # genuinely differs between the two layouts.
    @_memo
    def elastic_mesh(self):
        from mpisppy_tpu.parallel import mesh as mesh_mod
        return self.mesh if self.mesh is not None \
            else mesh_mod.make_mesh(1)

    @_memo
    def elastic_shrunk_mesh(self):
        import jax

        from mpisppy_tpu.parallel import elastic, mesh as mesh_mod
        devs = elastic.survivor_devices(jax.devices(), 2, [1])
        return mesh_mod.make_mesh(devices=devs)

    def _elastic_batch(self, mesh):
        from mpisppy_tpu import scengen
        from mpisppy_tpu.models import farmer as farmer_model
        from mpisppy_tpu.parallel import mesh as mesh_mod
        prog = farmer_model.scenario_program(7, seed=0)
        return mesh_mod.shard_batch(scengen.virtual_batch(prog), mesh,
                                    pad=True)

    @_memo
    def elastic_full(self):
        return self._elastic_batch(self.elastic_mesh)

    @_memo
    def elastic_shrunk(self):
        return self._elastic_batch(self.elastic_shrunk_mesh)

    def _elastic_fused_state(self, batch):
        import jax.numpy as jnp

        from mpisppy_tpu.algos import fused_wheel as fw
        rho = jnp.ones(batch.num_nonants, jnp.float32)
        fst, _, _ = fw.fused_iter0(batch, rho, self.ph_opts,
                                   self.wheel_opts)
        return fst

    @_memo
    def elastic_full_state(self):
        return self._elastic_fused_state(self.elastic_full)

    @_memo
    def elastic_shrunk_state(self):
        return self._elastic_fused_state(self.elastic_shrunk)


# ---------------------------------------------------------------------------
# builders (each: Fixtures -> (jitted_fn, args))
# ---------------------------------------------------------------------------
def _b_ph_iter0(fx):
    from mpisppy_tpu.algos import ph as ph_mod
    return ph_mod.ph_iter0, (fx.farmer, fx.rho, fx.ph_opts)


def _b_ph_iterk(fx):
    from mpisppy_tpu.algos import ph as ph_mod
    return ph_mod.ph_iterk, (fx.farmer, fx.ph_state, fx.ph_opts)


def _b_ph_eobjective(fx):
    from mpisppy_tpu.algos import ph as ph_mod
    return ph_mod.ph_eobjective, (fx.farmer, fx.ph_state)


def _b_fused_iter0(fx):
    from mpisppy_tpu.algos import fused_wheel as fw
    return fw.fused_iter0, (fx.farmer, fx.rho, fx.ph_opts,
                            fx.wheel_opts)


def _b_fused_iterk(fx):
    from mpisppy_tpu.algos import fused_wheel as fw
    return fw.fused_iterk, (fx.farmer, fx.fused_state, fx.ph_opts,
                            fx.wheel_opts, fx.shuffle_id)


def _b_lag_plane(fx):
    from mpisppy_tpu.algos import fused_wheel as fw
    fst = fx.fused_state
    return fw.lag_plane, (fx.farmer, fst.ph.W, fst.lag_solver,
                          fx.wheel_opts, 2)


def _b_xhat_plane(fx):
    from mpisppy_tpu.algos import fused_wheel as fw
    fst = fx.fused_state
    return fw.xhat_plane, (fx.farmer, fx.xhat_cand, fst.xhat_solver,
                           fx.wheel_opts, 2)


def _b_slam_plane(fx):
    from mpisppy_tpu.algos import fused_wheel as fw
    fst = fx.fused_state
    return fw.slam_plane, (fx.farmer, fst.ph.solver.x, fst.slam_solver,
                           fx.wheel_opts, 1, True)


def _b_shuf_plane(fx):
    from mpisppy_tpu.algos import fused_wheel as fw
    fst = fx.fused_state
    return fw.shuf_plane, (fx.farmer, fst.ph.solver.x, fst.shuf_solver,
                           fx.shuffle_id, fx.wheel_opts, 1)


def _b_ph_stale_step(fx):
    from mpisppy_tpu.algos import fused_wheel as fw
    plane = fw.plane_of(fx.ph_state)
    return fw.ph_stale_step, (fx.farmer, fx.ph_state, plane,
                              fx.ph_opts)


def _b_xhat_evaluate(fx):
    from mpisppy_tpu.algos import xhat as xhat_mod
    return xhat_mod._evaluate_core, (fx.farmer, fx.xhat_cand,
                                     fx.pdhg_opts, 1e-3)


def _b_xhat_evaluate_warm(fx):
    from mpisppy_tpu.algos import xhat as xhat_mod
    fst = fx.fused_state
    return xhat_mod._evaluate_warm_core, (fx.farmer, fx.xhat_cand,
                                          fst.xhat_solver,
                                          fx.pdhg_opts, 1e-3)


def _b_xhat_shuffle(fx):
    import jax.numpy as jnp
    from mpisppy_tpu.algos import xhat as xhat_mod
    scen_ids = jnp.arange(2, dtype=jnp.int32)
    x_non = fx.farmer.nonants(fx.ph_state.solver.x)
    return xhat_mod.xhat_shuffle, (fx.farmer, x_non, scen_ids, 2,
                                   fx.pdhg_opts)


def _b_fwph_init(fx):
    from mpisppy_tpu.algos import fwph as fwph_mod
    return fwph_mod.fwph_init, (fx.farmer, fx.rho, fx.fwph_opts)


def _b_fwph_iter(fx):
    from mpisppy_tpu.algos import fwph as fwph_mod
    return fwph_mod.fwph_iter, (fx.farmer, fx.fwph_state, fx.fwph_opts)


def _b_lshaped_cuts(fx):
    from mpisppy_tpu.algos import lshaped as ls_mod
    xhat0 = fx.ph_state.xbar_nodes[0]
    return ls_mod._subproblem_cuts, (fx.farmer, xhat0, fx.pdhg_opts)


@functools.lru_cache(maxsize=1)
def _cross_scen_probe():
    """Module-level jit of the dry run's probe impl — one shared
    compile cache, same trace as dryrun_multichip's."""
    import jax
    import __graft_entry__ as ge
    return jax.jit(ge._cross_scen_probe_impl, static_argnames=("opts",))


def _b_cross_scen_cuts(fx):
    st = fx.ph_state
    return _cross_scen_probe(), (fx.farmer, st.xbar * 1.01, st.xbar,
                                 fx.pdhg_opts)


@functools.lru_cache(maxsize=1)
def _mpc_shift_kernel():
    """The MPC warm-start shift kernel's process-wide jit — the SAME
    executable mpc.shift.shift_state dispatches (shared lazy global,
    so the audit and a live stream trace one cache entry)."""
    import jax

    from mpisppy_tpu.mpc import shift as shift_mod
    if shift_mod._shift_state_jit is None:
        shift_mod._shift_state_jit = jax.jit(shift_mod._shift_state_impl)
    return shift_mod._shift_state_jit


def _b_mpc_shift(fx):
    import jax.numpy as jnp

    from mpisppy_tpu.mpc import shift as shift_mod
    st = fx.ph_state
    # a stride-1 persistence plan over the farmer nonant axis — the
    # same roll + fresh-tail gather shape every horizon emits
    plan = shift_mod.uc_plan(1, fx.farmer.num_nonants)
    x_non = fx.farmer.nonants(st.solver.x)
    return _mpc_shift_kernel(), (st.W, st.xbar_nodes, x_non,
                                 jnp.asarray(plan.src_idx),
                                 jnp.asarray(plan.fresh_mask))


def _b_bnb_round(fx):
    from mpisppy_tpu.ops import bnb as bnb_mod
    int_cols, bst = fx.bnb_state
    b = fx.sslp
    return bnb_mod.bnb_round, (b.qp, b.d_col, int_cols, bst,
                               fx.bnb_opts)


def _b_pdhg_solve_loop(fx):
    from mpisppy_tpu.ops import pdhg
    return pdhg._solve_loop_jit, (fx.sslp.qp, fx.pdhg_opts,
                                  fx.pdhg_init)


def _b_pdhg_solve_fixed(fx):
    from mpisppy_tpu.ops import pdhg
    return pdhg._solve_fixed_jit, (fx.sslp.qp, 2, fx.pdhg_opts,
                                   fx.pdhg_init)


def _b_pallas_window(fx):
    from mpisppy_tpu.ops import pdhg_pallas as pp
    st = fx.pdhg_init
    tau = 0.9 * st.omega / st.Lnorm
    sigma = 0.9 / (st.omega * st.Lnorm)
    return pp.run_window, (fx.sslp.qp, st.x, st.y, st.x_sum, st.y_sum,
                           tau, sigma, st.done, 4, 8, None, True,
                           True, None)


def _b_scengen_realize(fx):
    from mpisppy_tpu.scengen import virtual as virt
    return virt._realize_jit, (fx.virtual,)


def _b_ph_iter0_virtual(fx):
    from mpisppy_tpu.algos import ph as ph_mod
    return ph_mod.ph_iter0, (fx.virtual, fx.virtual_rho, fx.ph_opts)


def _b_ph_iterk_virtual(fx):
    from mpisppy_tpu.algos import ph as ph_mod
    return ph_mod.ph_iterk, (fx.virtual, fx.virtual_ph_state,
                             fx.ph_opts)


def _b_fused_iterk_elastic(fx):
    from mpisppy_tpu.algos import fused_wheel as fw
    return fw.fused_iterk, (fx.elastic_full, fx.elastic_full_state,
                            fx.ph_opts, fx.wheel_opts, fx.shuffle_id)


def _b_fused_iterk_reshard(fx):
    from mpisppy_tpu.algos import fused_wheel as fw
    return fw.fused_iterk, (fx.elastic_shrunk, fx.elastic_shrunk_state,
                            fx.ph_opts, fx.wheel_opts, fx.shuffle_id)


def _b_ckpt_gather(fx):
    # a scenario-sharded solver-x plane stands in for the fused state's
    # leaf: same sharding + dtype, no fused_iter0 compile on the fast
    # subset's critical path (the 60s tier-1 budget)
    import jax
    import jax.numpy as jnp

    from mpisppy_tpu.cylinders import hub as hub_mod
    from mpisppy_tpu.parallel import mesh as mesh_mod
    b = fx.elastic_full
    ndev = fx.elastic_mesh.devices.size
    s_pad = -(-b.num_scenarios // ndev) * ndev
    x = jax.device_put(
        jnp.zeros((s_pad, b.num_nonants), jnp.float32),
        mesh_mod.scen_sharding(fx.elastic_mesh))
    fn = hub_mod._replicated_gather(fx.elastic_mesh)
    return fn, (x,)


# ---------------------------------------------------------------------------
# the manifest
# ---------------------------------------------------------------------------
_AR = frozenset({"all-reduce"})
_AR_CP = frozenset({"all-reduce", "collective-permute"})
_AG_AR = frozenset({"all-gather", "all-reduce"})
_AG_AR_CP = frozenset({"all-gather", "all-reduce", "collective-permute"})

#: scengen transients budget: the audit programs realize a ~few-KB
#: farmer batch in-trace; a compiled high-water above this means an
#: S-major tensor outlived its transient (the contract the pass holds).
#: Generous vs the measured ~0.4-9 KB high-waters, tight vs any real
#: S-major residency creep — and the KERNEL_IR.json +10% temp-bytes
#: ratchet pins the actual number far below it.
_VIRTUAL_TEMP_BUDGET = 1 << 20      # 1 MiB

MANIFEST: tuple[KernelSpec, ...] = (
    KernelSpec("ph_iter0", _b_ph_iter0,
               "PH iter0: plain solves + W seed + trivial bound",
               collectives=_AR_CP, sharded=True, fast=True),
    KernelSpec("ph_iterk", _b_ph_iterk,
               "one PH iteration (the hub hot step)",
               collectives=_AR, sharded=True, fast=True),
    KernelSpec("ph_eobjective", _b_ph_eobjective,
               "E[f_s(x_s)] at current iterates",
               collectives=_AR, sharded=True, fast=True),
    KernelSpec("fused_iter0", _b_fused_iter0,
               "fused wheel iter0 (hub + 4 bound planes)",
               collectives=_AR_CP, sharded=True),
    KernelSpec("fused_iterk", _b_fused_iterk,
               "fused wheel iteration (monolithic plane program)",
               collectives=_AG_AR, sharded=True),
    KernelSpec("lag_plane", _b_lag_plane,
               "split-dispatch Lagrangian bound plane",
               collectives=_AR, sharded=True),
    KernelSpec("xhat_plane", _b_xhat_plane,
               "split-dispatch xhat recourse-evaluation plane",
               collectives=_AG_AR, sharded=True),
    KernelSpec("slam_plane", _b_slam_plane,
               "split-dispatch slam-heuristic plane",
               collectives=_AR, sharded=True),
    KernelSpec("shuf_plane", _b_shuf_plane,
               "split-dispatch shuffle-candidate plane",
               collectives=_AG_AR, sharded=True),
    KernelSpec("ph_stale_step", _b_ph_stale_step,
               "APH-class stale-plane hub step (async wheel)",
               collectives=_AR, sharded=True, fast=True),
    KernelSpec("xhat_evaluate", _b_xhat_evaluate,
               "xhat evaluate core (fixed-nonant recourse)",
               collectives=_AR_CP, sharded=True, fast=True),
    KernelSpec("xhat_evaluate_warm", _b_xhat_evaluate_warm,
               "warm-state xhat evaluate core",
               collectives=_AR, sharded=True),
    KernelSpec("xhat_shuffle", _b_xhat_shuffle,
               "k-candidate shuffle evaluation",
               collectives=_AR_CP, sharded=True),
    KernelSpec("fwph_init", _b_fwph_init,
               "FWPH init (iter0 solves + column seed)",
               collectives=_AR_CP, sharded=True),
    KernelSpec("fwph_iter", _b_fwph_iter,
               "FWPH SDM iteration",
               collectives=_AG_AR_CP, sharded=True),
    KernelSpec("lshaped_cuts", _b_lshaped_cuts,
               "L-shaped per-scenario cut extraction",
               collectives=_AR_CP, sharded=True, fast=True),
    KernelSpec("cross_scen_cuts", _b_cross_scen_cuts,
               "cross-scenario cut launch (winner argmax)",
               collectives=_AG_AR_CP, sharded=True),
    KernelSpec("bnb_round", _b_bnb_round,
               "batched-MIP best-first B&B round",
               collectives=_AR, sharded=True, fast=True),
    KernelSpec("pdhg_solve_loop", _b_pdhg_solve_loop,
               "host-level PDHG solve loop (shape-keyed jit)",
               fast=True),
    KernelSpec("pdhg_solve_fixed", _b_pdhg_solve_fixed,
               "fixed-window PDHG solve (shape-keyed jit)",
               fast=True),
    KernelSpec("pallas_window", _b_pallas_window,
               "Pallas restart window, interpret mode (CPU trace of "
               "the double-buffered pipeline engine)"),
    KernelSpec("scengen_realize", _b_scengen_realize,
               "VirtualBatch.realize jitted whole-batch synthesis",
               virtual=True, temp_budget_bytes=_VIRTUAL_TEMP_BUDGET,
               fast=True),
    KernelSpec("ph_iter0_virtual", _b_ph_iter0_virtual,
               "PH iter0 fed by a VirtualBatch (concretize path)",
               collectives=_AR_CP, sharded=True, virtual=True,
               temp_budget_bytes=_VIRTUAL_TEMP_BUDGET, fast=True),
    KernelSpec("ph_iterk_virtual", _b_ph_iterk_virtual,
               "PH iteration fed by a VirtualBatch (concretize path)",
               collectives=_AR, sharded=True, virtual=True,
               temp_budget_bytes=_VIRTUAL_TEMP_BUDGET, fast=True),
    KernelSpec("fused_iterk_elastic", _b_fused_iterk_elastic,
               "elastic hub hot step at the FULL topology (sharded "
               "VirtualBatch, prime S padded for the full mesh)",
               collectives=_AG_AR, sharded=True, virtual=True,
               temp_budget_bytes=_VIRTUAL_TEMP_BUDGET),
    KernelSpec("fused_iterk_reshard", _b_fused_iterk_reshard,
               "elastic hub hot step at the SHRUNK (survivor) "
               "topology — the shape run_elastic recompiles after a "
               "host loss; single survivor, so no collectives",
               virtual=True, temp_budget_bytes=_VIRTUAL_TEMP_BUDGET),
    KernelSpec("mpc_shift_state", _b_mpc_shift,
               "MPC warm-start shift: (W, xbar_nodes, x) rolled along "
               "the nonant axis by a traced src_idx/fresh_mask gather "
               "— every stream step re-dispatches one executable",
               fast=True),
    KernelSpec("ckpt_gather", _b_ckpt_gather,
               "replicated checkpoint gather (hub._replicated_gather "
               "— the bounded collective under emergency saves)",
               collectives=frozenset({"all-gather"}), sharded=True,
               fast=True),
)

_BY_NAME = {s.name: s for s in MANIFEST}


def spec(name: str) -> KernelSpec:
    return _BY_NAME[name]


def declared_collectives(kernel: str) -> frozenset | None:
    """The exact collective kinds declared for a sharded kernel, or
    None when the kernel is not in the manifest / not sharded (the
    __graft_entry__ dry run falls back to its legacy check then)."""
    s = _BY_NAME.get(kernel)
    if s is None or not s.sharded:
        return None
    return s.collectives


def names(subset: str = "full") -> list[str]:
    """Kernel names in `subset` ('full' or the tier-1 'fast' set)."""
    return [s.name for s in MANIFEST if subset == "full" or s.fast]
