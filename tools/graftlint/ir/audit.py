###############################################################################
# graftlint IR layer: abstract lowering + fact extraction (ISSUE 15).
#
# For every manifest kernel this module derives one KernelFacts record
# from two artifacts the AST can't see:
#
#   * the closed JAXPR (fn.trace(*args).jaxpr) — concrete array
#     constants (the recompile-leak class), the dtype census over every
#     equation variable (recursively through pjit/scan/while/cond
#     sub-jaxprs), and host-callback primitives
#     (pure_callback/io_callback/debug_callback);
#   * the CPU-compiled executable — memory_analysis temp/arg/output
#     high-water bytes, cost_analysis flop estimate, and (on a >= 2
#     device mesh) the collective ops in the SPMD-partitioned HLO text.
#
# HLO facts ride behind a jaxpr-hash lowering cache (--ir-cache /
# GRAFTLINT_IR_CACHE): the cache key is sha256 over (kernel name, jax
# version, backend, device count, jaxpr pretty-print), so an unchanged
# kernel costs one trace and zero compiles on re-runs — that is what
# holds the tier-1 time budget.  Jaxpr-level facts are recomputed every
# run (tracing is cheap; compiling is not).
#
# Device bring-up: collectives only exist in >= 2 device lowerings.
# ensure_devices() forces the virtual-CPU device count via XLA_FLAGS
# *before* jax initializes — callers that already initialized jax
# single-device (an in-process pytest run) simply get no sharded facts
# (facts.collectives is None, passes skip), which is why the tier-1 IR
# tests drive the CLI in a subprocess.
###############################################################################
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import sys

from tools.graftlint.ir import manifest

_COLLECTIVE_RE = re.compile("|".join(manifest.COLLECTIVE_KINDS))
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback")
_CACHE_ENV = "GRAFTLINT_IR_CACHE"


@dataclasses.dataclass
class KernelFacts:
    """Everything the five IR passes judge, plus the KERNEL_IR.json
    payload."""

    name: str
    path: str = ""                  # repo-relative source of the kernel
    line: int = 1
    const_bytes: int = 0            # total bytes of jaxpr array consts
    consts: list = dataclasses.field(default_factory=list)
    dtype_census: dict = dataclasses.field(default_factory=dict)
    f64_count: int = 0
    callbacks: list = dataclasses.field(default_factory=list)
    collectives: list | None = None  # None = no sharded lowering ran
    temp_bytes: int = 0
    arg_bytes: int = 0
    out_bytes: int = 0
    flops: float = 0.0
    cached: bool = False            # HLO facts served from the cache

    def artifact_entry(self) -> dict:
        """The KERNEL_IR.json per-kernel record (gate surface: the
        regress GATES ratchet const_bytes any-increase and temp_bytes
        +10%; the rest is recorded for diffing and the passes)."""
        return {
            "const_bytes": self.const_bytes,
            "n_consts": len(self.consts),
            "dtype_census": dict(sorted(self.dtype_census.items())),
            "callbacks": list(self.callbacks),
            "collectives": sorted(self.collectives or []),
            "temp_bytes": self.temp_bytes,
            "arg_bytes": self.arg_bytes,
            "out_bytes": self.out_bytes,
            "flops": self.flops,
        }


# ---------------------------------------------------------------------------
# device bring-up
# ---------------------------------------------------------------------------
def ensure_devices(n: int = 2) -> None:
    """Arrange for >= n virtual CPU devices.  Must run before jax
    initializes; a no-op (callers degrade to unsharded facts) when jax
    is already up."""
    if "jax" in sys.modules:
        return
    flag = f"--xla_force_host_platform_device_count={n}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()


def device_count() -> int:
    import jax
    return len(jax.devices())


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------
def _sub_jaxprs(eqn):
    for pv in eqn.params.values():
        vals = pv if isinstance(pv, (list, tuple)) else (pv,)
        for sub in vals:
            if hasattr(sub, "jaxpr") and hasattr(sub, "consts"):
                yield sub.jaxpr, list(sub.consts)     # ClosedJaxpr
            elif hasattr(sub, "eqns"):
                yield sub, []                         # raw Jaxpr


def _walk_jaxpr(jaxpr, census: dict, callbacks: list, consts: list,
                seen: set) -> None:
    if id(jaxpr) in seen:
        return
    seen.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _CALLBACK_PRIMS:
            callbacks.append(eqn.primitive.name)
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None:
                key = str(dt)
                census[key] = census.get(key, 0) + 1
        for sub, sub_consts in _sub_jaxprs(eqn):
            consts.extend(sub_consts)
            _walk_jaxpr(sub, census, callbacks, consts, seen)


def _const_records(consts) -> tuple[int, list]:
    """(total bytes, [{shape, dtype, nbytes}]) over array consts at or
    above the manifest threshold; scalars and tiny index helpers are
    idiomatic and skipped."""
    total = 0
    records = []
    seen_ids = set()
    for c in consts:
        if id(c) in seen_ids:
            continue
        seen_ids.add(id(c))
        nbytes = int(getattr(c, "nbytes", 0) or 0)
        total += nbytes
        if nbytes >= manifest.CONST_BYTES_THRESHOLD:
            records.append({
                "shape": list(getattr(c, "shape", ())),
                "dtype": str(getattr(c, "dtype", "?")),
                "nbytes": nbytes,
            })
    return total, records


# ---------------------------------------------------------------------------
# lowering cache
# ---------------------------------------------------------------------------
def cache_dir() -> str | None:
    return os.environ.get(_CACHE_ENV) or None


def _cache_key(name: str, jaxpr_text: str, devices: int) -> str:
    import jax
    h = hashlib.sha256()
    for part in (name, jax.__version__, jax.default_backend(),
                 str(devices), jaxpr_text):
        h.update(part.encode())
        h.update(b"\0")
    return h.hexdigest()


def _cache_get(cdir: str | None, key: str) -> dict | None:
    if not cdir:
        return None
    path = os.path.join(cdir, key + ".json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _cache_put(cdir: str | None, key: str, value: dict) -> None:
    if not cdir:
        return
    try:
        os.makedirs(cdir, exist_ok=True)
        tmp = os.path.join(cdir, key + ".tmp")
        with open(tmp, "w") as f:
            json.dump(value, f)
        os.replace(tmp, os.path.join(cdir, key + ".json"))
    except OSError:
        pass                    # cache is best-effort by design


# ---------------------------------------------------------------------------
# per-kernel audit
# ---------------------------------------------------------------------------
def _source_site(fn, root: str) -> tuple[str, int]:
    """Repo-relative (path, line) of the kernel's def — the Finding
    anchor (and where an inline `# graftlint: allow-ir-*` would go)."""
    import inspect
    target = fn
    for attr in ("__wrapped__", "_fun", "func"):
        inner = getattr(target, attr, None)
        if inner is not None:
            target = inner
            break
    try:
        path = inspect.getsourcefile(target)
        _, line = inspect.getsourcelines(target)
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
        return rel.replace(os.sep, "/"), line
    except (TypeError, OSError):
        return "tools/graftlint/ir/manifest.py", 1


def _flops_of(cost) -> float:
    entry = cost[0] if isinstance(cost, (list, tuple)) and cost else cost
    if isinstance(entry, dict):
        v = entry.get("flops")
        if isinstance(v, (int, float)) and v >= 0:
            return float(v)
    return 0.0


def audit_kernel(spec, fx, root: str, sharded_fx=None,
                 cdir: str | None = None) -> KernelFacts:
    """Build one kernel and derive its facts.  `fx` is the unsharded
    Fixtures; `sharded_fx` (a mesh-carrying Fixtures, or None) feeds
    the collective facts."""
    fn, args = spec.build(fx)
    facts = KernelFacts(name=spec.name)
    facts.path, facts.line = _source_site(fn, root)

    traced = fn.trace(*args)
    closed = traced.jaxpr
    census: dict = {}
    callbacks: list = []
    consts = list(closed.consts)
    _walk_jaxpr(closed.jaxpr, census, callbacks, consts, set())
    facts.dtype_census = census
    facts.f64_count = sum(n for dt, n in census.items()
                          if dt in ("float64", "complex128"))
    facts.callbacks = sorted(set(callbacks))
    facts.const_bytes, facts.consts = _const_records(consts)

    jaxpr_text = str(closed)
    key = _cache_key(spec.name, jaxpr_text, 1)
    hlo_facts = _cache_get(cdir, key)
    if hlo_facts is None:
        compiled = traced.lower().compile()
        mem = compiled.memory_analysis()
        hlo_facts = {
            "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
            "arg_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
            "out_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
            "flops": _flops_of(compiled.cost_analysis()),
        }
        _cache_put(cdir, key, hlo_facts)
    else:
        facts.cached = True
    facts.temp_bytes = hlo_facts["temp_bytes"]
    facts.arg_bytes = hlo_facts["arg_bytes"]
    facts.out_bytes = hlo_facts["out_bytes"]
    facts.flops = hlo_facts["flops"]

    if spec.sharded and sharded_fx is not None:
        sfn, sargs = spec.build(sharded_fx)
        straced = sfn.trace(*sargs)
        skey = _cache_key(spec.name, str(straced.jaxpr),
                          sharded_fx.mesh.devices.size)
        cached = _cache_get(cdir, skey)
        if cached is not None and "collectives" in cached:
            facts.collectives = cached["collectives"]
        else:
            hlo = straced.lower().compile().as_text()
            facts.collectives = sorted(set(_COLLECTIVE_RE.findall(hlo)))
            _cache_put(cdir, skey, {"collectives": facts.collectives})
    return facts


def audit_kernels(specs, root: str, devices: int | None = None,
                  cdir: str | None = None) -> dict[str, KernelFacts]:
    """Audit `specs` (manifest KernelSpecs or compatible fixture specs)
    sharing one Fixtures pair.  `devices=None` = shard when the running
    backend has >= 2 devices."""
    fx = manifest.Fixtures()
    sharded_fx = None
    want = device_count() if devices is None else devices
    if want >= 2 and any(s.sharded for s in specs):
        if device_count() >= 2:
            from mpisppy_tpu.parallel import mesh as mesh_mod
            sharded_fx = manifest.Fixtures(mesh=mesh_mod.make_mesh(2))
    out = {}
    for s in specs:
        out[s.name] = audit_kernel(s, fx, root, sharded_fx=sharded_fx,
                                   cdir=cdir)
    return out


def run_manifest(root: str, subset: str = "full",
                 cdir: str | None = None) -> dict[str, KernelFacts]:
    """The full audit entry point used by the IR passes and the
    artifact emitter."""
    ensure_devices(2)
    specs = [s for s in manifest.MANIFEST
             if subset == "full" or s.fast]
    return audit_kernels(specs, root, cdir=cdir or cache_dir())


def to_artifact(facts: dict[str, KernelFacts],
                subset: str = "full") -> dict:
    import jax
    return {
        "schema": "mpisppy-tpu-kernel-ir/1",
        "jax": jax.__version__,
        "subset": subset,
        "kernels": {name: f.artifact_entry()
                    for name, f in sorted(facts.items())},
    }
