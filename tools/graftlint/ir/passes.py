###############################################################################
# graftlint IR layer: the five IR passes (ISSUE 15).
#
#   ir-const-capture       concrete array constants >= 1 KiB baked into
#                          a kernel's jaxpr — the PR-4/PR-9 per-value
#                          recompile-leak class caught structurally for
#                          every manifest kernel, forever
#   ir-dtype-census        f64 leaves/promotions inside kernels under
#                          the docs/precision.md f32/bf16x3 contract
#   ir-host-boundary       pure_callback/io_callback/debug_callback
#                          primitives inside hot kernels — IR truth
#                          replacing the lexical host-sync heuristic
#   ir-collective-manifest sharded lowerings must contain EXACTLY their
#                          declared collectives, both directions (the
#                          per-kernel generalization of the dry run's
#                          one-off HLO asserts)
#   ir-memory-high-water   compiled temp-byte high-water; VirtualBatch-
#                          fed kernels must stay under their declared
#                          transients budget (the scengen "scenario
#                          data exists only as transients" contract,
#                          machine-checked)
#
# Each rule's `run(ctx)` audits the manifest once per scan (memoized on
# the Context identity) and only against the repo this tools tree lives
# in — a fixture mini-repo has no kernel manifest, so the IR rules are
# structurally silent there and the seeded-violation tests drive the
# per-rule `*_findings(spec, facts)` functions directly.
###############################################################################
from __future__ import annotations

import os

from tools.graftlint.core import Context, Finding, Rule
from tools.graftlint.ir import manifest

_HOME = os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

#: audit subset the rules run ('full' | 'fast'); the CLI sets this
#: (--ir-subset) before run_rules — tier-1 drives the fast subset
SUBSET = "full"

_MEMO: dict[tuple[int, str], tuple] = {}


def set_subset(subset: str) -> None:
    global SUBSET
    if subset not in ("full", "fast"):
        raise ValueError(f"ir subset must be 'full' or 'fast', "
                         f"got {subset!r}")
    SUBSET = subset


def _audit_for(ctx: Context):
    """(facts dict, error, state) for the scanned repo, or None when
    the scan is not auditable: a root that is not the repo owning this
    manifest (fixture trees), or a PATH-RESTRICTED scan — the IR audit
    is a whole-manifest affair (kernels live all over the tree), so
    `python -m tools.graftlint some/dir` stays an AST-only scan rather
    than compiling 24 kernels and reporting findings outside the
    requested paths."""
    if os.path.abspath(ctx.root) != _HOME or getattr(ctx, "scoped", False):
        return None
    key = (id(ctx), SUBSET)
    if key not in _MEMO:
        try:
            from tools.graftlint.ir import audit
            facts = audit.run_manifest(ctx.root, subset=SUBSET)
            _MEMO[key] = (facts, None, {})
        except Exception as e:          # surfaced as a finding, once
            _MEMO[key] = (None, f"{type(e).__name__}: {e}", {})
    return _MEMO[key]


# ---------------------------------------------------------------------------
# per-rule finding functions (pure over (spec, facts) — the seeded
# fixture tests call these directly)
# ---------------------------------------------------------------------------
def const_capture_findings(spec, facts) -> list[Finding]:
    out = []
    for i, rec in enumerate(facts.consts):
        shape = "x".join(str(d) for d in rec["shape"]) or "scalar"
        out.append(Finding(
            "ir-const-capture", facts.path, facts.line,
            f"kernel {spec.name}: concrete {rec['dtype']}[{shape}] "
            f"constant ({rec['nbytes']} bytes) baked into the jaxpr — "
            f"a closed-over array traces as a CONSTANT, so every "
            f"distinct value recompiles (the PR-4 leak class); thread "
            f"it through the kernel's arguments instead",
            key=f"ir::{spec.name}::const::{rec['dtype']}[{shape}]#{i}"))
    return out


def dtype_census_findings(spec, facts) -> list[Finding]:
    if not facts.f64_count:
        return []
    wide = {dt: n for dt, n in facts.dtype_census.items()
            if dt in ("float64", "complex128")}
    return [Finding(
        "ir-dtype-census", facts.path, facts.line,
        f"kernel {spec.name}: {facts.f64_count} f64 equation "
        f"variable(s) in the traced IR ({wide}) — hot kernels hold the "
        f"docs/precision.md f32/bf16x3 contract; keep f64 on the host "
        f"side of the boundary",
        key=f"ir::{spec.name}::f64")]


def host_boundary_findings(spec, facts) -> list[Finding]:
    return [Finding(
        "ir-host-boundary", facts.path, facts.line,
        f"kernel {spec.name}: {kind} primitive inside the traced "
        f"kernel — a host round trip serializes every dispatch of a "
        f"hot kernel; hoist it to the harvest/exchange boundary",
        key=f"ir::{spec.name}::callback::{kind}")
        for kind in facts.callbacks]


def collective_manifest_findings(spec, facts) -> list[Finding]:
    if not spec.sharded or facts.collectives is None:
        return []
    found = set(facts.collectives)
    declared = set(spec.collectives)
    out = []
    for kind in sorted(declared - found):
        out.append(Finding(
            "ir-collective-manifest", facts.path, facts.line,
            f"kernel {spec.name}: sharded lowering is MISSING declared "
            f"collective {kind!r} — the kernel no longer communicates "
            f"where the manifest says it must (a silently-local "
            f"reduction computes the wrong answer per shard)",
            key=f"ir::{spec.name}::collective-missing::{kind}"))
    for kind in sorted(found - declared):
        out.append(Finding(
            "ir-collective-manifest", facts.path, facts.line,
            f"kernel {spec.name}: sharded lowering contains UNDECLARED "
            f"collective {kind!r} — declare it in the manifest "
            f"(tools/graftlint/ir/manifest.py) or remove the "
            f"communication",
            key=f"ir::{spec.name}::collective-extra::{kind}"))
    return out


def memory_high_water_findings(spec, facts) -> list[Finding]:
    if not spec.virtual or spec.temp_budget_bytes is None:
        return []
    if facts.temp_bytes <= spec.temp_budget_bytes:
        return []
    return [Finding(
        "ir-memory-high-water", facts.path, facts.line,
        f"kernel {spec.name}: compiled temp high-water "
        f"{facts.temp_bytes} bytes exceeds the VirtualBatch transients "
        f"budget {spec.temp_budget_bytes} — an S-major tensor is being "
        f"materialized beyond the realize() transient "
        f"(docs/scengen.md: scenario data exists only as transients)",
        key=f"ir::{spec.name}::temp-high-water")]


_FINDERS = {
    "ir-const-capture": const_capture_findings,
    "ir-dtype-census": dtype_census_findings,
    "ir-host-boundary": host_boundary_findings,
    "ir-collective-manifest": collective_manifest_findings,
    "ir-memory-high-water": memory_high_water_findings,
}


def _make_run(rule_name: str):
    def run(ctx: Context) -> list[Finding]:
        res = _audit_for(ctx)
        if res is None:
            return []
        facts, err, state = res
        if err is not None:
            # a broken audit must never read as a clean repo: whichever
            # SELECTED ir-* rule runs first reports it (exactly once
            # per scan, whatever the rule subset)
            if state.get("err_reported"):
                return []
            state["err_reported"] = True
            return [Finding(
                rule_name, "tools/graftlint/ir/audit.py", 1,
                f"IR audit failed to run: {err}",
                key="ir-audit-failed")]
        finder = _FINDERS[rule_name]
        out = []
        for name, f in sorted(facts.items()):
            out.extend(finder(manifest.spec(name), f))
        return out
    return run


def kernel_counts() -> dict[str, int]:
    """rule name -> number of manifest kernels the pass covers (the
    --rules listing; importing this never touches jax)."""
    all_n = len(manifest.MANIFEST)
    return {
        "ir-const-capture": all_n,
        "ir-dtype-census": all_n,
        "ir-host-boundary": all_n,
        "ir-collective-manifest":
            sum(1 for s in manifest.MANIFEST if s.sharded),
        "ir-memory-high-water":
            sum(1 for s in manifest.MANIFEST if s.virtual),
    }


IR_RULES = (
    Rule("ir-const-capture",
         "concrete array constants baked into kernel jaxprs "
         "(per-value recompile leak, IR-level)",
         _make_run("ir-const-capture")),
    Rule("ir-dtype-census",
         "f64 leaves/promotions inside kernels under the f32/bf16x3 "
         "precision contract",
         _make_run("ir-dtype-census")),
    Rule("ir-host-boundary",
         "host callback primitives inside hot kernels (IR truth for "
         "the host boundary)",
         _make_run("ir-host-boundary")),
    Rule("ir-collective-manifest",
         "sharded lowerings contain exactly their declared "
         "collectives, both directions",
         _make_run("ir-collective-manifest")),
    Rule("ir-memory-high-water",
         "VirtualBatch-fed kernels stay under their compiled "
         "temp-bytes transients budget",
         _make_run("ir-memory-high-water")),
)
