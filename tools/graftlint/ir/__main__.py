###############################################################################
# KERNEL_IR.json emitter: `python -m tools.graftlint.ir --emit
# KERNEL_IR.json [--subset fast|full] [--cache DIR]`.
#
# Runs the manifest audit and writes (or prints) the artifact the
# regress gates ratchet: per-kernel const bytes (any-increase), temp
# bytes (+10%), plus the dtype census / collective list / flop estimate
# recorded for diffing.  Sets the virtual-CPU device count BEFORE jax
# initializes so the sharded collective facts exist.
###############################################################################
from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint.ir",
        description="IR-level kernel audit artifact emitter "
                    "(docs/static_analysis.md, IR layer)")
    ap.add_argument("--emit", help="write KERNEL_IR.json here "
                                   "(default: print to stdout)")
    ap.add_argument("--subset", choices=("full", "fast"),
                    default="full")
    ap.add_argument("--cache",
                    help="lowering cache dir (default: "
                         "$GRAFTLINT_IR_CACHE)")
    ap.add_argument("--devices", type=int, default=2,
                    help="virtual CPU devices for sharded facts")
    ns = ap.parse_args(argv)

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    if root not in sys.path:
        sys.path.insert(0, root)
    if ns.cache:
        os.environ["GRAFTLINT_IR_CACHE"] = ns.cache

    from tools.graftlint.ir import audit
    audit.ensure_devices(ns.devices)
    facts = audit.run_manifest(root, subset=ns.subset)
    artifact = audit.to_artifact(facts, subset=ns.subset)
    text = json.dumps(artifact, indent=1, sort_keys=True)
    if ns.emit:
        with open(ns.emit, "w") as f:
            f.write(text + "\n")
        cached = sum(1 for f_ in facts.values() if f_.cached)
        print(f"wrote {ns.emit}: {len(facts)} kernels "
              f"({cached} lowering(s) from cache)")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
