###############################################################################
# lock-discipline: a lightweight race detector for the serving layer.
#
# PR 8 turned dispatch/scheduler.py into a ~900-line multithreaded
# server with hand-rolled `self._lock` discipline and nothing checking
# it.  This pass makes the discipline declarative: a shared field is
# ANNOTATED at its __init__ assignment
#
#     self._batches = 0          # guarded-by: _lock
#
# and every later `self._batches` read/write must sit lexically inside
# a `with self._lock:` block (or a lock-aliased condition — a field
# built as `threading.Condition(self._lock)` shares its lock, so
# `with self._wake:` also holds `_lock`).  Helper methods documented
# as "caller holds the lock" declare it machine-readably on the def
# line:
#
#     def _ensure_dispatcher(self):   # holds-lock: _lock
#
# Scope and soundness: analysis is lexical and per-class — it cannot
# see a lock held across a call boundary without the holds-lock
# marker, and it treats any access inside the right `with` as guarded
# (no alias/escape analysis).  That is the useful trade: annotations
# cost one comment per field, violations are almost always real (or
# real documentation debt), and the pass forced a genuine audit of
# every scheduler field when it landed (two lost-update races found —
# see the ISSUE-10 commit).  __init__ (and __new__) are exempt:
# construction happens-before publication.
###############################################################################
from __future__ import annotations

import ast
import re

from tools.graftlint.core import Context, Finding, Rule

RULE_NAME = "lock-discipline"

GUARDED_RE = re.compile(
    r"self\.(\w+)\s*(?::[^=#]+)?=.*#\s*guarded-by:\s*(\w+)")
HOLDS_RE = re.compile(r"#\s*holds-lock:\s*(\w+)")
CTOR_EXEMPT = {"__init__", "__new__", "__post_init__"}


def _with_locks(item: ast.withitem) -> str | None:
    """`with self.<lock>:` -> lock name."""
    e = item.context_expr
    if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
            and e.value.id == "self":
        return e.attr
    return None


def _class_annotations(ctx: Context, rel: str, cls: ast.ClassDef):
    """(guarded: field -> lock, aliases: condvar field -> lock) from
    the class body's source lines."""
    lines = ctx.lines(rel)
    end = max((n.end_lineno for n in ast.walk(cls)
               if getattr(n, "end_lineno", None) is not None),
              default=cls.lineno)
    guarded: dict[str, str] = {}
    for ln in range(cls.lineno, min(end, len(lines)) + 1):
        m = GUARDED_RE.search(lines[ln - 1])
        if m:
            guarded[m.group(1)] = m.group(2)
    aliases: dict[str, str] = {}
    for node in ast.walk(cls):
        # self._wake = threading.Condition(self._lock)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "Condition" and call.args:
                a = call.args[0]
                if isinstance(a, ast.Attribute) \
                        and isinstance(a.value, ast.Name) \
                        and a.value.id == "self":
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) \
                                and isinstance(tgt.value, ast.Name) \
                                and tgt.value.id == "self":
                            aliases[tgt.attr] = a.attr
    return guarded, aliases


def _check_method(ctx: Context, rel: str, cls_name: str,
                  fn: ast.FunctionDef, guarded: dict[str, str],
                  aliases: dict[str, str]) -> list[Finding]:
    lines = ctx.lines(rel)
    base_held: set[str] = set()
    m = HOLDS_RE.search(lines[fn.lineno - 1])
    if m:
        base_held.add(m.group(1))
    out: list[Finding] = []
    seen: set[tuple[str, int]] = set()

    def walk(node: ast.AST, held: frozenset[str]) -> None:
        if isinstance(node, ast.With):
            add = set()
            for item in node.items:
                lk = _with_locks(item)
                if lk is not None:
                    add.add(lk)
                    if lk in aliases:
                        add.add(aliases[lk])
            for item in node.items:
                walk(item, held)
            inner = held | frozenset(add)
            for b in node.body:
                walk(b, inner)
            return
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name) and node.value.id == "self" \
                and node.attr in guarded:
            lock = guarded[node.attr]
            if lock not in held and (node.attr, node.lineno) not in seen:
                seen.add((node.attr, node.lineno))
                out.append(Finding(
                    RULE_NAME, rel, node.lineno,
                    f"{cls_name}.{node.attr} is `# guarded-by: {lock}` "
                    f"but accessed in {fn.name}() outside `with "
                    f"self.{lock}` (add the lock, or mark the def "
                    f"`# holds-lock: {lock}` if the caller holds it)",
                    key=f"{rel}::{cls_name}.{fn.name}::{node.attr}"))
        # nested defs inherit nothing (they may run on another thread)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            for child in ast.iter_child_nodes(node):
                walk(child, frozenset())
            return
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    if fn.name in CTOR_EXEMPT:
        return []
    for stmt in fn.body:
        walk(stmt, frozenset(base_held))
    return out


def run(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    for rel in ctx.files:
        if "# guarded-by:" not in ctx.source(rel):
            continue
        try:
            tree = ctx.tree(rel)
        except SyntaxError:
            continue
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            guarded, aliases = _class_annotations(ctx, rel, node)
            if not guarded:
                continue
            for b in node.body:
                if isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.extend(_check_method(ctx, rel, node.name, b,
                                             guarded, aliases))
    return out


RULE = Rule(RULE_NAME,
            "`# guarded-by:` fields touched outside their lock "
            "(threaded modules)", run)
