###############################################################################
# schema-drift: the telemetry taxonomy is kept consistent by machine,
# not by reviewer memory.  Five sub-checks, one rule:
#
#   1. EMIT KINDS — every event kind emitted anywhere in the library
#      (`bus.emit("...")`, `self._emit(tel.X, ...)`,
#      `self._emit_event("...", ...)`) must be declared in
#      telemetry/events.py (the uppercase string constants whose union
#      is ALL_KINDS).  A typo'd kind silently fragments the trace —
#      sinks store it, the analyzer drops it.
#   2. DOC ROWS — every declared kind must have a row in
#      docs/telemetry.md's event table, and every backticked kind in
#      the table must still be declared (both drift directions).
#   3. METRICS — every literal metric name at a
#      REGISTRY.inc/set_gauge/set_counter/get call site must be
#      declared in telemetry/metrics.py ALL_METRICS (the registry this
#      pass forced into existence).  Names passed as variables are
#      skipped (documented approximation — the declared registry still
#      anchors them for humans).
#   4. GATE KEYS — every GATES/MILESTONES pattern in
#      telemetry/regress.py must match at least one metric key
#      produced by a COMMITTED artifact: the BENCH_r*/BENCH_DETAIL/
#      DEVICE_PROFILE/SSLP_CERT/KERNEL_IR JSON files plus analyzer
#      reports derived from the committed tests/fixtures/
#      golden_*.jsonl traces.  A gate nothing can produce is dead
#      armor — it looks like protection and gates nothing.
#   5. REPORT SCHEMAS — every versioned `*_SCHEMA` identifier the
#      tooling modules declare (analyze / spans / slo) must be
#      documented in docs/telemetry.md, and the TRACE schema
#      (`mpisppy-tpu-trace/1`) must additionally be WITNESSED: at
#      least one committed golden fixture with trace-context rows
#      must assemble into a zero-orphan span tree carrying that
#      schema.  A schema no committed fixture produces is dead
#      vocabulary; an orphaned golden trace is a dropped propagation
#      hop checked into the repo.
#
# Events/metrics declarations are read by AST (no import of the
# package under scan); the gate-key check loads telemetry/regress.py
# and analyze.py standalone BY PATH (stdlib-only modules) so the key
# flattening can never drift from the real gate's.
###############################################################################
from __future__ import annotations

import ast
import glob
import os
import re
import sys

from tools.graftlint.core import Context, Finding, Rule

RULE_NAME = "schema-drift"


# -- declared vocabularies (AST, no imports) --------------------------------
def declared_kinds(ctx: Context):
    """(kind -> lineno, events.py relpath, CONST name -> kind), or
    None when the scanned tree has no events module."""
    rel = f"{ctx.lib_dir}/telemetry/events.py"
    if not os.path.exists(ctx.abspath(rel)):
        return None
    kinds: dict[str, int] = {}
    consts: dict[str, str] = {}
    for node in ctx.tree(rel).body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.isupper() \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value
            kinds[node.value.value] = node.lineno
    return kinds, rel, consts


def declared_schemas(ctx: Context) -> dict[str, tuple[str, int]]:
    """Versioned report-schema identifiers (`*_SCHEMA = "..."` module
    constants) declared by the telemetry tooling modules."""
    out: dict[str, tuple[str, int]] = {}
    for rel in (f"{ctx.lib_dir}/telemetry/analyze.py",
                f"{ctx.lib_dir}/telemetry/spans.py",
                f"{ctx.lib_dir}/telemetry/slo.py"):
        if not os.path.exists(ctx.abspath(rel)):
            continue
        for node in ctx.tree(rel).body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.endswith("_SCHEMA") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                out[node.value.value] = (rel, node.lineno)
    return out


def declared_metrics(ctx: Context):
    rel = f"{ctx.lib_dir}/telemetry/metrics.py"
    if not os.path.exists(ctx.abspath(rel)):
        return None, rel
    for node in ast.walk(ctx.tree(rel)):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "ALL_METRICS":
            names = set()
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    names.add(sub.value)
            return names, rel
    return None, rel


# -- call-site extraction ---------------------------------------------------
_EMIT_WRAPPER_NAMES = {"_emit", "_emit_event"}
_METRIC_METHODS = {"inc", "set_gauge", "set_counter", "observe"}


def _forwarding_wrappers(tree: ast.AST) -> set[str]:
    """Module-local wrapper names whose FIRST parameter is forwarded
    verbatim as the kind of an inner `.emit(...)` call (hub._emit,
    scheduler._emit_event).  A wrapper whose first param is NOT the
    kind (profiler._emit forwards `action` into the data payload of a
    fixed ev.PROFILE) is excluded — its call sites are not kind
    sites."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in _EMIT_WRAPPER_NAMES):
            continue
        params = [a.arg for a in node.args.args if a.arg != "self"]
        if not params:
            continue
        p0 = params[0]
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "emit" and sub.args \
                    and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id == p0:
                out.add(node.name)
    return out


def _emitted_kinds(ctx: Context, consts: dict[str, str]):
    """[(rel, line, kind, resolved)] for every emit call site with a
    statically-known kind.  `tel.X` / `ev.X` attribute kinds resolve
    through the events-module constants; an attribute that does NOT
    resolve is reported with resolved=False (a constant that was
    deleted but is still referenced would crash at import — caught
    earlier — so in practice this means a non-events alias)."""
    sites = []
    for rel in ctx.files:
        if rel.endswith("telemetry/events.py"):
            continue
        try:
            tree = ctx.tree(rel)
        except SyntaxError:
            continue
        wrappers = {"emit"} | _forwarding_wrappers(tree)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in wrappers
                    and node.args):
                continue
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                sites.append((rel, node.lineno, a0.value, True))
            elif isinstance(a0, ast.Attribute) \
                    and isinstance(a0.value, ast.Name) \
                    and a0.value.id in ("tel", "ev", "events"):
                kind = consts.get(a0.attr)
                sites.append((rel, node.lineno,
                              kind if kind is not None else a0.attr,
                              kind is not None))
    return sites


def _metric_sites(ctx: Context):
    sites = []
    for rel in ctx.files:
        try:
            tree = ctx.tree(rel)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _METRIC_METHODS and node.args:
                recv = ast.unparse(node.func.value)
                if not (recv.endswith("REGISTRY") or recv == "R"
                        or recv.endswith("registry")):
                    continue
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) \
                        and isinstance(a0.value, str):
                    sites.append((rel, node.lineno, a0.value))
    return sites


# -- doc table --------------------------------------------------------------
def doc_table_kinds(ctx: Context, doc_rel: str = "docs/telemetry.md"):
    """Backticked kinds in the first cell of the event-table rows.
    Combined rows (`run-start`/`run-end`) contribute each kind."""
    path = ctx.abspath(doc_rel)
    if not os.path.exists(path):
        return None
    kinds: dict[str, int] = {}
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            if not line.startswith("|"):
                continue
            first = line.split("|")[1]
            for m in re.finditer(r"`([\w-]+)`", first):
                kinds.setdefault(m.group(1), ln)
    return kinds


# -- gate-key resolution ----------------------------------------------------
def _load_by_path(ctx: Context, rel: str, name: str):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        f"_graftlint_{name}", ctx.abspath(rel))
    mod = importlib.util.module_from_spec(spec)
    prev = sys.modules.get(spec.name)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        if prev is not None:
            sys.modules[spec.name] = prev
        else:
            sys.modules.pop(spec.name, None)
    return mod


def committed_key_pool(ctx: Context, regress) -> set[str]:
    pool: set[str] = set()
    for pat in ("BENCH_r[0-9]*.json", "BENCH_DETAIL.json",
                "DEVICE_PROFILE.json", "SSLP_CERT.json",
                "KERNEL_IR.json"):
        for p in sorted(glob.glob(os.path.join(ctx.root, pat))):
            try:
                pool |= set(regress.extract_metrics(
                    regress.load_artifact(p)))
            except (OSError, ValueError):
                continue
    # analyzer reports over the committed golden trace fixtures:
    # analyze.py imports sibling telemetry modules via the package —
    # load through the package only if importable from ctx.root,
    # else skip (a stripped test repo still lints its own artifacts)
    fixtures = sorted(glob.glob(os.path.join(
        ctx.root, "tests", "fixtures", "golden_*.jsonl")))
    if fixtures:
        try:
            sys.path.insert(0, ctx.root)
            from importlib import import_module
            an = import_module(f"{ctx.lib_dir}.telemetry.analyze")
            for fx in fixtures:
                try:
                    pool |= set(regress.extract_metrics(
                        an.analyze_path(fx)))
                except Exception:
                    continue
        except Exception:
            pass
        finally:
            if sys.path and sys.path[0] == ctx.root:
                sys.path.pop(0)
    return pool


def run(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    ev = declared_kinds(ctx)
    if ev is None:
        return out      # not a repo with a telemetry spine: nothing to do
    kinds, ev_rel, consts = ev

    # 1. emitted kinds must be declared
    for rel, line, kind, resolved in _emitted_kinds(ctx, consts):
        if not resolved:
            out.append(Finding(
                RULE_NAME, rel, line,
                f"event kind attribute `{kind}` does not resolve "
                f"against {ev_rel} constants",
                key=f"{rel}::emit-unresolved::{kind}"))
        elif kind not in kinds:
            out.append(Finding(
                RULE_NAME, rel, line,
                f"emitted event kind {kind!r} is not declared in "
                f"{ev_rel} (ALL_KINDS) — a typo'd kind fragments the "
                f"trace silently",
                key=f"{rel}::emit::{kind}"))

    # 2. declared kinds <-> doc table rows
    doc = doc_table_kinds(ctx)
    if doc is not None:
        for kind, line in sorted(kinds.items()):
            if kind not in doc:
                out.append(Finding(
                    RULE_NAME, ev_rel, line,
                    f"event kind {kind!r} has no row in "
                    f"docs/telemetry.md's event table",
                    key=f"doc-missing::{kind}"))
        for kind, line in sorted(doc.items()):
            if kind not in kinds and "-" in kind:
                # hyphenless backticked tokens in the table are field
                # names, not kinds; every real kind is hyphenated
                # except the declared ones checked above
                if kind in ("flight-recorder",):
                    continue    # dump-file-only header kind (flightrec)
                out.append(Finding(
                    RULE_NAME, "docs/telemetry.md", line,
                    f"doc event-table row {kind!r} has no declared "
                    f"kind in {ev_rel}",
                    key=f"doc-stale::{kind}"))

    # 3. metric literals must be registered
    metrics, m_rel = declared_metrics(ctx)
    if metrics is None:
        out.append(Finding(
            RULE_NAME, m_rel, 1,
            "telemetry/metrics.py declares no ALL_METRICS registry — "
            "metric names have no schema to drift against",
            key="no-metric-registry"))
    else:
        for rel, line, name in _metric_sites(ctx):
            if name not in metrics:
                out.append(Finding(
                    RULE_NAME, rel, line,
                    f"metric {name!r} is not declared in {m_rel} "
                    f"ALL_METRICS",
                    key=f"{rel}::metric::{name}"))

    # 4. GATES/MILESTONES must resolve against committed artifacts
    reg_rel = f"{ctx.lib_dir}/telemetry/regress.py"
    if os.path.exists(ctx.abspath(reg_rel)):
        try:
            regress = _load_by_path(ctx, reg_rel, "regress")
        except Exception as e:   # unparseable regress: surface, move on
            out.append(Finding(RULE_NAME, reg_rel, 1,
                               f"could not load regress.py: {e}",
                               key="regress-unloadable"))
            return out
        pool = committed_key_pool(ctx, regress)
        if pool:
            tables = [("GATES", getattr(regress, "GATES", ())),
                      ("MILESTONES", getattr(regress, "MILESTONES", ()))]
            src = ctx.source(reg_rel)
            for table, rows in tables:
                for pat, _direction, _thr in rows:
                    if any(re.search(pat, k) for k in pool):
                        continue
                    line = next((i for i, ln in enumerate(
                        src.splitlines(), 1) if pat in ln
                        or pat.replace("\\", "") in ln), 1)
                    out.append(Finding(
                        RULE_NAME, reg_rel, line,
                        f"{table} pattern {pat!r} matches no metric "
                        f"key of any committed artifact (BENCH_*/"
                        f"DEVICE_PROFILE/SSLP_CERT/KERNEL_IR/"
                        f"golden-trace analyzer report) — a gate "
                        f"nothing produces gates nothing",
                        key=f"gate-unresolved::{pat}"))

    # 5. report schemas: documented, and the trace schema witnessed by
    #    a committed zero-orphan golden fixture
    schemas = declared_schemas(ctx)
    doc_path = ctx.abspath("docs/telemetry.md")
    doc_src = ""
    if os.path.exists(doc_path):
        with open(doc_path) as f:
            doc_src = f.read()
    if doc_src:
        for schema, (rel, line) in sorted(schemas.items()):
            if schema not in doc_src:
                out.append(Finding(
                    RULE_NAME, rel, line,
                    f"report schema {schema!r} is not documented in "
                    f"docs/telemetry.md",
                    key=f"schema-undocumented::{schema}"))
    spans_rel = f"{ctx.lib_dir}/telemetry/spans.py"
    trace_schemas = sorted(s for s in schemas if "-trace/" in s)
    if trace_schemas and os.path.exists(ctx.abspath(spans_rel)):
        try:
            spans_mod = _load_by_path(ctx, spans_rel, "spans")
        except Exception as e:
            out.append(Finding(RULE_NAME, spans_rel, 1,
                               f"could not load spans.py: {e}",
                               key="spans-unloadable"))
            spans_mod = None
        witnessed: set[str] = set()
        if spans_mod is not None:
            for fx in sorted(glob.glob(os.path.join(
                    ctx.root, "tests", "fixtures", "golden_*.jsonl"))):
                fx_rel = os.path.relpath(fx, ctx.root)
                try:
                    if not spans_mod.trace_ids(spans_mod.load_rows(fx)):
                        continue    # pre-trace-context fixture: no rows
                    rep = spans_mod.assemble_path(fx, trace="last")
                except Exception as e:
                    out.append(Finding(
                        RULE_NAME, fx_rel, 1,
                        f"golden trace fixture does not assemble into "
                        f"a span tree: {e}",
                        key=f"fixture-unassemblable::{fx_rel}"))
                    continue
                if rep.get("schema") not in schemas:
                    out.append(Finding(
                        RULE_NAME, fx_rel, 1,
                        f"assembled fixture carries undeclared schema "
                        f"{rep.get('schema')!r}",
                        key=f"fixture-schema::{fx_rel}"))
                    continue
                if rep.get("orphans"):
                    out.append(Finding(
                        RULE_NAME, fx_rel, 1,
                        f"committed golden trace has "
                        f"{len(rep['orphans'])} orphan span(s) — a "
                        f"dropped trace-propagation hop is checked in",
                        key=f"fixture-orphans::{fx_rel}"))
                    continue
                witnessed.add(rep["schema"])
            for schema in trace_schemas:
                if schema not in witnessed:
                    out.append(Finding(
                        RULE_NAME, spans_rel, 1,
                        f"trace schema {schema!r} is witnessed by no "
                        f"committed golden fixture (no tests/fixtures/"
                        f"golden_*.jsonl with trace-context rows "
                        f"assembles to it)",
                        key=f"schema-unwitnessed::{schema}"))
    return out


RULE = Rule(RULE_NAME,
            "event kinds vs ALL_KINDS vs docs table; metric names vs "
            "ALL_METRICS; GATES/MILESTONES vs committed artifacts", run)
