###############################################################################
# graftlint CLI: `python -m tools.graftlint [--json] [paths]`.
# Exit 0 = clean (baselined findings are reported but don't fail),
# exit 1 = active findings or baseline errors (stale/unjustified).
###############################################################################
from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    from tools import graftlint
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="project static-analysis suite "
                    "(docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: mpisppy_tpu/)")
    ap.add_argument("--json", action="store_true",
                    help="machine report (schema graftlint-report/1)")
    ap.add_argument("--rules",
                    help="comma-separated subset of rule names")
    ap.add_argument("--baseline",
                    help="baseline file (default: the committed "
                         "tools/graftlint/baseline.json)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the tree this tool "
                         "lives in)")
    ap.add_argument("--list-rules", action="store_true")
    ns = ap.parse_args(argv)

    if ns.list_rules:
        for r in graftlint.ALL_RULES:
            print(f"{r.name:<16} {r.doc}")
        return 0

    root = ns.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    rules = ns.rules.split(",") if ns.rules else None
    rep = graftlint.lint(root, paths=ns.paths or None, rules=rules,
                         baseline_path=ns.baseline)
    if ns.json:
        print(json.dumps(rep, indent=2))
    else:
        from tools.graftlint.core import Finding
        for f in rep["findings"]:
            print(Finding(**f).render())
        for e in rep["errors"]:
            print(f"ERROR: {e}")
        n = rep["active"]
        print(f"graftlint: {n} active finding(s), "
              f"{rep['baselined']} baselined, "
              f"{len(rep['errors'])} error(s) "
              f"[rules: {', '.join(rep['rules'])}]")
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
