###############################################################################
# graftlint CLI: `python -m tools.graftlint [--json] [paths]`.
# Exit 0 = clean (baselined findings are reported but don't fail),
# exit 1 = active findings or baseline errors (stale/unjustified).
#
# `--rules` with NO value lists every rule with its one-line doc — IR
# rules additionally show how many manifest kernels they cover; with a
# value it selects a comma-separated subset.  `--ir-cache DIR` (or
# $GRAFTLINT_IR_CACHE) points the IR audit's jaxpr-hash lowering cache
# somewhere CI and local runs can share; `--ir-subset fast` restricts
# the audit to the tier-1 manifest subset.
###############################################################################
from __future__ import annotations

import argparse
import json
import os
import sys

_LIST = "__list__"


def _list_rules(graftlint) -> None:
    from tools.graftlint.ir import kernel_counts
    counts = kernel_counts()
    for r in graftlint.ALL_RULES:
        extra = ""
        if r.name in counts:
            extra = f"  [{counts[r.name]} kernels]"
        print(f"{r.name:<24} {r.doc}{extra}")


def main(argv=None) -> int:
    from tools import graftlint
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="project static-analysis suite "
                    "(docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: mpisppy_tpu/)")
    ap.add_argument("--json", action="store_true",
                    help="machine report (schema graftlint-report/1)")
    ap.add_argument("--rules", nargs="?", const=_LIST, default=None,
                    help="comma-separated subset of rule names; with "
                         "no value, list all rules (IR rules with "
                         "their kernel counts)")
    ap.add_argument("--baseline",
                    help="baseline file (default: the committed "
                         "tools/graftlint/baseline.json)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the tree this tool "
                         "lives in)")
    ap.add_argument("--ir-cache", metavar="DIR",
                    help="IR lowering cache dir (default: "
                         "$GRAFTLINT_IR_CACHE)")
    ap.add_argument("--ir-subset", choices=("full", "fast"),
                    default="full",
                    help="kernel-manifest subset the IR passes audit")
    ap.add_argument("--list-rules", action="store_true",
                    help="alias for bare --rules")
    ns = ap.parse_args(argv)

    if ns.list_rules or ns.rules == _LIST:
        _list_rules(graftlint)
        return 0

    if ns.ir_cache:
        os.environ["GRAFTLINT_IR_CACHE"] = ns.ir_cache
    rules = ns.rules.split(",") if ns.rules else None
    if rules is None or any(r.startswith("ir-") for r in rules):
        # multi-device facts need the virtual device count set before
        # jax initializes — a no-op when jax is already up (the passes
        # then degrade to unsharded facts)
        from tools.graftlint.ir import audit, set_subset
        audit.ensure_devices(2)
        set_subset(ns.ir_subset)

    root = ns.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    rep = graftlint.lint(root, paths=ns.paths or None, rules=rules,
                         baseline_path=ns.baseline)
    if ns.json:
        print(json.dumps(rep, indent=2))
    else:
        from tools.graftlint.core import Finding
        for f in rep["findings"]:
            print(Finding(**f).render())
        for e in rep["errors"]:
            print(f"ERROR: {e}")
        n = rep["active"]
        print(f"graftlint: {n} active finding(s), "
              f"{rep['baselined']} baselined, "
              f"{len(rep['errors'])} error(s) "
              f"[rules: {', '.join(rep['rules'])}]")
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
