###############################################################################
# readme-claims (graftlint pass 7; formerly tools/check_readme_claims.py
# — which remains as a thin shim over this module).  ISSUE 5 satellite;
# VERDICT r5 item: "README numbers drift from the driver-captured
# artifacts".
#
# Every performance number quoted in README's measured-results section
# (the block opening with "Measured on" and closing at "Out of scope")
# must trace to a committed benchmark artifact: a numeric field of
# BENCH_DETAIL.json, DEVICE_PROFILE.json (trace-derived device
# profiles, ISSUE 7) or any BENCH_rNN.json (including numbers inside a
# wrapper's possibly-truncated stdout `tail`).  "Performance number"
# means a number carrying a perf unit — seconds, x-factors, percents,
# iterations, iters/s, TFLOPs, GB/s; config numbers ("900 scenarios",
# "3-stage") are not claims and are ignored.
#
# Matching is display-precision aware: a README "102.7 s" traces to an
# artifact 102.66 (round-to-shown-digits), a "0.99%" to a 0.009910
# rel_gap (percent <-> fraction), and a "~" prefix marks an
# approximation allowed APPROX_REL_TOL relative slack.  Numbers with
# no artifact witness are violations: the artifacts are the evidence,
# the README quotes them — never better local runs.
#
# Second check (ISSUE 8): every measured-section bullet quoting a
# solver-throughput claim (seconds-to-gap, sec/iter, iters/s) must
# disclose the iteration-precision mode it was measured at
# (docs/precision.md) — bf16x3 halves the per-matvec byte traffic, so
# a throughput number without its mode is not a reproducible claim.
###############################################################################
from __future__ import annotations

import glob
import json
import os
import re

from tools.graftlint.core import Context, Finding, Rule

RULE_NAME = "readme-claims"

SECTION_START = "Measured on"
SECTION_END = "Out of scope"

#: perf units that make a number a checkable claim (longest first so
#: "iters/s" wins over a bare "s")
UNITS = ("iters/s", "iterations", "seconds", "TFLOPs", "TFLOP",
         "GB/s", "sec", "%", "x", "s")
CLAIM_RE = re.compile(
    r"(~?)(-?\d+(?:\.\d+)?)\s*(" + "|".join(
        re.escape(u) + (r"\b" if u[-1].isalnum() else "")
        for u in UNITS) + r")")

NUM_RE = re.compile(r"-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?")

APPROX_REL_TOL = 0.10   # slack granted to "~"-marked approximations

PRECISION_TOKENS = ("bf16x3", "bf16x6", "full precision")
SPEED_UNITS = {"s", "sec", "seconds", "iters/s"}


def _collect_numbers(obj, pool: set) -> None:
    """Every number in a JSON artifact — including numbers embedded in
    string values (bench notes, truncated stdout tails)."""
    if isinstance(obj, bool):
        return
    if isinstance(obj, (int, float)):
        pool.add(float(obj))
    elif isinstance(obj, str):
        for m in NUM_RE.finditer(obj):
            try:
                pool.add(float(m.group()))
            except ValueError:
                pass
    elif isinstance(obj, list):
        for v in obj:
            _collect_numbers(v, pool)
    elif isinstance(obj, dict):
        for v in obj.values():
            _collect_numbers(v, pool)
        # derived witnesses: the speedup-vs-baseline factor a README
        # naturally quotes next to a to-gap phase ("~1.8x faster")
        if isinstance(obj.get("seconds_to_gap"), (int, float)):
            for base_key in ("baseline_64rank_sec", "baseline_1rank_sec"):
                base = obj.get(base_key)
                if isinstance(base, (int, float)) \
                        and obj["seconds_to_gap"]:
                    pool.add(base / obj["seconds_to_gap"])


def artifact_pool(repo: str) -> set:
    pool: set = set()
    paths = sorted(glob.glob(os.path.join(repo, "BENCH_r[0-9]*.json")))
    for extra in ("BENCH_DETAIL.json", "DEVICE_PROFILE.json"):
        p = os.path.join(repo, extra)
        if os.path.exists(p):
            paths.append(p)
    for p in paths:
        try:
            with open(p) as f:
                _collect_numbers(json.load(f), pool)
        except (OSError, ValueError):
            continue
    return pool


def _measured_section(text: str) -> list[tuple[int, str]]:
    """The measured-results block's (lineno, line) pairs — THE one
    slicing rule both sub-checks scan, so they can never drift onto
    different sections."""
    lines = text.splitlines()
    start = next((i for i, ln in enumerate(lines)
                  if SECTION_START in ln), None)
    if start is None:
        return []
    end = next((i for i in range(start + 1, len(lines))
                if lines[i].startswith(SECTION_END)), len(lines))
    return [(i + 1, lines[i]) for i in range(start, end)]


def claims_in(text: str) -> list[tuple[str, float, int, str, int]]:
    """(display, value, decimals, unit, lineno) perf claims in the
    measured section; `display` keeps the ~ marker."""
    out = []
    for lineno, ln in _measured_section(text):
        for m in CLAIM_RE.finditer(ln):
            approx, num, unit = m.group(1), m.group(2), m.group(3)
            decimals = len(num.split(".")[1]) if "." in num else 0
            out.append((approx + num + unit, float(num), decimals, unit,
                        lineno))
    return out


def undisclosed_precision_bullets(text: str) -> list[tuple[int, str]]:
    """(lineno, head) of measured-section bullets carrying a
    speed-unit claim but no precision-mode token.  Bullets are grouped
    ('- ' starts one; indented lines continue it) so a disclosure
    anywhere in the bullet covers its wrapped lines."""
    bullets: list[tuple[int, str]] = []
    cur: tuple[int, str] | None = None
    for lineno, ln in _measured_section(text):
        if ln.lstrip().startswith("- "):
            if cur is not None:
                bullets.append(cur)
            cur = (lineno, ln)
        elif cur is not None and ln[:1] in (" ", "\t") and ln.strip():
            cur = (cur[0], cur[1] + "\n" + ln)
        elif cur is not None:
            # blank line or unindented prose ends the bullet — trailing
            # section paragraphs must not donate their disclosure token
            bullets.append(cur)
            cur = None
    if cur is not None:
        bullets.append(cur)
    bad = []
    for lineno, b in bullets:
        has_speed = any(m.group(3) in SPEED_UNITS
                        for m in CLAIM_RE.finditer(b))
        disclosed = any(tok in b.lower() for tok in PRECISION_TOKENS)
        if has_speed and not disclosed:
            bad.append((lineno, b.strip().splitlines()[0]))
    return bad


def _matches(value: float, decimals: int, approx: bool, unit: str,
             pool: set) -> bool:
    tol = 0.5 * 10.0 ** (-decimals)
    for v in pool:
        cands = (v, v * 100.0) if unit == "%" else (v,)
        for c in cands:
            if abs(value - c) <= tol:
                return True
            if approx and c and abs(value - c) <= APPROX_REL_TOL * abs(c):
                return True
    return False


def check_readme(readme_path: str, pool: set) -> list[Finding]:
    rel = os.path.basename(readme_path)
    try:
        with open(readme_path) as f:
            text = f.read()
    except OSError:
        return []
    seen = set()
    out: list[Finding] = []
    for display, value, decimals, unit, lineno in claims_in(text):
        if display in seen:
            continue
        seen.add(display)
        if not _matches(value, decimals, display.startswith("~"), unit,
                        pool):
            out.append(Finding(
                RULE_NAME, rel, lineno,
                f"perf claim {display!r} has no witness in "
                f"BENCH_DETAIL.json / BENCH_r[0-9]*.json / "
                f"DEVICE_PROFILE.json — quote the committed artifact, "
                f"not a local run",
                key=f"claim::{display}"))
    for lineno, head in undisclosed_precision_bullets(text):
        out.append(Finding(
            RULE_NAME, rel, lineno,
            f"throughput claim without an iteration-precision "
            f"disclosure (need one of {PRECISION_TOKENS} in the "
            f"bullet; docs/precision.md): {head[:80]!r}",
            key=f"precision::{head[:60]}"))
    return out


def run(ctx: Context) -> list[Finding]:
    readme = os.path.join(ctx.root, "README.md")
    if not os.path.exists(readme):
        return []
    return check_readme(readme, artifact_pool(ctx.root))


RULE = Rule(RULE_NAME,
            "README measured-section perf numbers must trace to "
            "committed BENCH artifacts (+ precision disclosure)", run)
