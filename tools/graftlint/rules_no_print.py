###############################################################################
# no-print (graftlint pass 6; formerly tools/lint_no_print.py, ISSUE 3
# satellite — tools/lint_no_print.py remains as a thin shim over this
# module so existing invocations keep working).
#
# Library code must report through the telemetry console
# (mpisppy_tpu.telemetry.console.log) so every human-readable line is
# verbosity-filtered and lands in the JSONL trace; a bare `print(` is
# invisible to both.  Allowed exceptions:
#
#   * the console/sink implementations themselves,
#   * __main__ / dryrun entry points (their stdout IS the product),
#   * lines carrying a `# telemetry: allow-print` marker — the CLI's
#     machine-readable JSON result protocol on stdout/stderr
#     (the graftlint-native `# graftlint: allow-no-print` works too).
###############################################################################
from __future__ import annotations

import re

from tools.graftlint.core import Context, Finding, Rule

RULE_NAME = "no-print"

ALLOWED_FILES = {
    "telemetry/console.py",   # the console sink of last resort
    "telemetry/sinks.py",     # ConsoleSink rendering
    "telemetry/__main__.py",  # trace-toolbox CLI (its stdout IS the
                              # product: reports + JSON)
    "telemetry/watch.py",     # live-monitor renderer (stdout IS the
                              # product: the refreshing status block)
    "__main__.py",            # CLI entry point
    "serve/__main__.py",      # serve-server CLI entry point (its
                              # stdout IS the product: the bound
                              # address + argv diagnostics)
    "parallel/_multihost_dryrun.py",  # multihost smoke entry point
    "confidence_intervals/mmw_conf.py",  # CLI entry point (JSON stdout)
    "resilience/watchdog.py",  # abort-path last words go straight to
                               # stderr: the telemetry console may be
                               # wedged inside the very stall the
                               # watchdog is escaping (ISSUE 9)
}

MARKER = "telemetry: allow-print"
PRINT_RE = re.compile(r"(?<![\w.])print\(")


def run(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    prefix = ctx.lib_dir + "/"
    for rel in ctx.files:
        short = rel[len(prefix):] if rel.startswith(prefix) else rel
        if short in ALLOWED_FILES:
            continue
        for lineno, line in enumerate(ctx.lines(rel), 1):
            # match only the code portion: a print( mentioned in a
            # comment (or the allow marker itself) is fine
            code = line.split("#", 1)[0]
            if PRINT_RE.search(code) and MARKER not in line:
                out.append(Finding(
                    RULE_NAME, rel, lineno,
                    f"bare print( — use mpisppy_tpu.telemetry.console"
                    f".log (or add `# {MARKER}` for CLI protocol "
                    f"output)",
                    key=f"{rel}::{lineno}"))
    return out


RULE = Rule(RULE_NAME,
            "bare print( in library code (route through the "
            "telemetry console)", run)
