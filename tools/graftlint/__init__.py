###############################################################################
# graftlint — the project's static-analysis suite (ISSUE 10;
# docs/static_analysis.md).
#
#   python -m tools.graftlint [--json] [--rules a,b] [paths]
#
# Seven AST passes over mpisppy_tpu/ (see docs/static_analysis.md for
# the rule catalog, suppression syntax and baseline workflow):
#
#   trace-purity     eager lax control flow / per-call jit wrappers —
#                    the PR-4 recompile-leak class, at lint time
#   lock-discipline  `# guarded-by:` fields touched outside their lock
#   host-sync        device->host syncs inside the iteration kernels
#   schema-drift     event kinds vs ALL_KINDS vs docs table; metric
#                    names vs ALL_METRICS; GATES/MILESTONES vs
#                    committed artifacts
#   config-knob      undeclared cfg reads + dead declared knobs
#   no-print         bare print( in library code
#   readme-claims    README perf numbers vs committed BENCH artifacts
#
# ...plus the IR layer (tools/graftlint/ir/, ISSUE 15): five passes
# over abstractly-lowered kernel jaxprs/HLO from the declarative
# kernel manifest — ir-const-capture, ir-dtype-census,
# ir-host-boundary, ir-collective-manifest, ir-memory-high-water —
# with per-kernel facts committed as KERNEL_IR.json and ratcheted by
# telemetry/regress.py GATES.
#
# When this package is imported with `tools` not on sys.path (the
# legacy shims add tools/ itself), the absolute `tools.graftlint`
# imports inside the rule modules still need the repo root — resolved
# here once.
###############################################################################
from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.graftlint.core import (  # noqa: E402,F401 (re-exports)
    BASELINE_SCHEMA, Context, Finding, Rule, load_baseline, run_rules,
)
from tools.graftlint import (  # noqa: E402
    rules_config_knob, rules_host_sync, rules_lock_discipline,
    rules_no_print, rules_readme_claims, rules_schema_drift,
    rules_trace_purity,
)
from tools.graftlint import ir as _ir  # noqa: E402

#: registration order = documentation order (docs/static_analysis.md)
AST_RULES = (
    rules_trace_purity.RULE,
    rules_lock_discipline.RULE,
    rules_host_sync.RULE,
    rules_schema_drift.RULE,
    rules_config_knob.RULE,
    rules_no_print.RULE,
    rules_readme_claims.RULE,
)

#: the IR layer (tools/graftlint/ir/): abstract-lowering passes over
#: the kernel manifest.  Part of the default rule set — `python -m
#: tools.graftlint` lints source AND compiled-artifact structure — but
#: kept addressable separately: the IR audit executes the kernels it
#: judges (the one sanctioned exception to import-free linting) and
#: wants a fresh process for multi-device facts, so in-process callers
#: (the tier-1 AST clean test) select AST_RULES and the tier-1 IR test
#: drives the CLI in a subprocess.
IR_RULES = _ir.IR_RULES

ALL_RULES = AST_RULES + IR_RULES

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def lint(root: str, paths: list[str] | None = None,
         rules: list[str] | None = None,
         baseline_path: str | None = None) -> dict:
    """Programmatic entry point (tests, the tier-1 wiring).  Returns
    the report dict (schema graftlint-report/1); report["ok"] is the
    pass/fail verdict."""
    selected = list(ALL_RULES)
    if rules:
        unknown = set(rules) - {r.name for r in ALL_RULES}
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}; "
                             f"have {[r.name for r in ALL_RULES]}")
        selected = [r for r in ALL_RULES if r.name in set(rules)]
    ctx = Context(root, paths=paths)
    if baseline_path is None:
        baseline_path = DEFAULT_BASELINE if os.path.abspath(
            root) == _REPO else None
    return run_rules(ctx, selected, baseline_path=baseline_path)
