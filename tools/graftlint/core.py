###############################################################################
# graftlint core: Finding/Rule model, scan context, suppressions,
# baseline round trip (ISSUE 10 tentpole; docs/static_analysis.md).
#
# The framework is deliberately boring: a Rule is a named callable over
# a Context (repo root + cached sources/ASTs of the library files); it
# returns Findings carrying file:line, a human message, and a STABLE
# `key` — the identity the baseline matches on, so grandfathered
# findings survive unrelated line drift.  Stdlib only: the lint must
# run on a host with no jax (and inside tier-1 without importing the
# library under scan — all analysis is AST/regex over source text; the
# one exception is rules_schema_drift loading telemetry/{regress,
# analyze}.py standalone BY PATH, which keeps "no import of the
# package under scan" true while reusing the real metric flattener).
#
# Two escape hatches, both per-finding and both auditable:
#   * inline suppression — `# graftlint: allow-<rule>` on the finding
#     line (or the immediately preceding comment line);
#   * the committed baseline (tools/graftlint/baseline.json) for
#     grandfathered findings, matched by (rule, key).  Every entry
#     MUST carry a non-empty `why` — a baseline without justification
#     is itself a lint failure — and entries matching nothing are
#     STALE failures, so the baseline can only shrink.
###############################################################################
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

BASELINE_SCHEMA = "graftlint-baseline/1"
SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*allow-([\w-]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str
    key: str           # stable identity for baseline matching
    baselined: bool = False

    def render(self) -> str:
        tag = "  [baselined]" if self.baselined else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "key": self.key,
                "baselined": self.baselined}


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    doc: str                     # one-line rule description (--list-rules)
    run: object                  # Context -> list[Finding]


class Context:
    """One scan: repo root, the library files in scope, and parse
    caches.  `paths` restricts the file set (CLI positional args);
    repo-level rules (schema-drift, config-knob, readme-claims) always
    read their anchor files relative to `root` regardless."""

    def __init__(self, root: str, paths: list[str] | None = None,
                 lib_dir: str = "mpisppy_tpu"):
        self.root = os.path.abspath(root)
        self.lib_dir = lib_dir
        #: path-restricted scan (CLI positional args) — whole-repo
        #: analyses (the IR audit) skip scoped scans
        self.scoped = bool(paths)
        self._src: dict[str, str] = {}
        self._lines: dict[str, list[str]] = {}
        self._tree: dict[str, ast.AST] = {}
        if paths:
            files: list[str] = []
            for p in paths:
                ap = p if os.path.isabs(p) else os.path.join(self.root, p)
                if os.path.isdir(ap):
                    files.extend(self._walk(ap))
                elif ap.endswith(".py"):
                    files.append(ap)
            self.files = sorted({self.rel(f) for f in files})
        else:
            lib = os.path.join(self.root, lib_dir)
            self.files = sorted(self.rel(f) for f in self._walk(lib))

    @staticmethod
    def _walk(top: str) -> list[str]:
        out = []
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            out.extend(os.path.join(dirpath, f) for f in filenames
                       if f.endswith(".py"))
        return out

    def rel(self, path: str) -> str:
        return os.path.relpath(os.path.abspath(path),
                               self.root).replace(os.sep, "/")

    def abspath(self, rel: str) -> str:
        return os.path.join(self.root, rel)

    def source(self, rel: str) -> str:
        if rel not in self._src:
            with open(self.abspath(rel)) as f:
                self._src[rel] = f.read()
        return self._src[rel]

    def lines(self, rel: str) -> list[str]:
        if rel not in self._lines:
            self._lines[rel] = self.source(rel).splitlines()
        return self._lines[rel]

    def tree(self, rel: str) -> ast.AST:
        if rel not in self._tree:
            self._tree[rel] = ast.parse(self.source(rel),
                                        filename=rel)
        return self._tree[rel]

    # -- suppression -------------------------------------------------------
    def suppressed(self, rel: str, line: int, rule: str) -> bool:
        """True when `line` (1-based) carries `# graftlint: allow-<rule>`
        or the immediately preceding line is a comment carrying it."""
        lines = self.lines(rel)
        for ln in (line, line - 1):
            if 1 <= ln <= len(lines):
                m = SUPPRESS_RE.search(lines[ln - 1])
                if m and m.group(1) == rule:
                    if ln == line or lines[ln - 1].lstrip().startswith("#"):
                        return True
        return False


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
def load_baseline(path: str) -> tuple[dict[tuple[str, str], dict],
                                      list[str]]:
    """Returns ({(rule, key): entry}, errors).  A missing file is an
    empty baseline; a malformed one (bad schema, entry without a
    non-empty `why`) is reported as errors — the justification IS the
    contract (ISSUE 10 acceptance)."""
    if not os.path.exists(path):
        return {}, []
    errors: list[str] = []
    try:
        with open(path) as f:
            obj = json.load(f)
    except ValueError as e:
        return {}, [f"baseline {path}: unparseable JSON ({e})"]
    if obj.get("schema") != BASELINE_SCHEMA:
        errors.append(f"baseline {path}: schema "
                      f"{obj.get('schema')!r} != {BASELINE_SCHEMA!r}")
    entries: dict[tuple[str, str], dict] = {}
    for i, e in enumerate(obj.get("entries", [])):
        rule, key = e.get("rule"), e.get("key")
        if not rule or not key:
            errors.append(f"baseline entry {i}: needs rule+key")
            continue
        if not str(e.get("why", "")).strip():
            errors.append(
                f"baseline entry {rule}:{key}: missing `why` — every "
                f"grandfathered finding needs a justification")
        entries[(rule, key)] = e
    return entries, errors


def apply_baseline(findings: list[Finding],
                   baseline: dict[tuple[str, str], dict],
                   ) -> tuple[list[Finding], list[str]]:
    """Mark baselined findings; report stale entries (matched nothing)
    as errors so the baseline can only shrink."""
    out = []
    hit: set[tuple[str, str]] = set()
    for f in findings:
        k = (f.rule, f.key)
        if k in baseline:
            hit.add(k)
            f = dataclasses.replace(f, baselined=True)
        out.append(f)
    stale = [f"stale baseline entry {r}:{k} — the finding is gone; "
             f"delete the entry" for (r, k) in sorted(set(baseline) - hit)]
    return out, stale


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------
def run_rules(ctx: Context, rules: list[Rule],
              baseline_path: str | None = None) -> dict:
    baseline, errors = load_baseline(baseline_path) \
        if baseline_path else ({}, [])
    findings: list[Finding] = []
    for rule in rules:
        for f in rule.run(ctx):
            if not ctx.suppressed(f.path, f.line, f.rule):
                findings.append(f)
    findings.sort(key=lambda f: (f.rule, f.path, f.line))
    findings, stale = apply_baseline(findings, baseline)
    errors.extend(stale)
    active = [f for f in findings if not f.baselined]
    return {
        "schema": "graftlint-report/1",
        "rules": [r.name for r in rules],
        "findings": [f.to_dict() for f in findings],
        "active": len(active),
        "baselined": len(findings) - len(active),
        "errors": errors,
        "ok": not active and not errors,
    }
